"""R2 signal-safety.

Walks the call graph reachable from every handler registered via
``signal.signal(...)`` and flags operations that can deadlock or
corrupt state when the interrupted frame already holds the resource —
the exact shape of the PR 3 SIGTERM hang, where the handler blocked on
the flight-recorder mutex held by the frame it interrupted:

* ``signal-unsafe-lock`` (error) — blocking lock acquisition
  (``with lock:`` or ``.acquire()`` without ``blocking=False``)
  reachable from a signal handler. Try-acquire is the safe idiom
  (``FlightRecorder.record_nowait``).
* ``signal-unsafe-logging`` (error) — stdlib ``logging`` calls; the
  logging machinery takes a module-level lock internally.
* ``signal-unsafe-blocking`` (error) — any other blocking call
  (sleep, subprocess, RPC, queue get) in the handler path.
* ``signal-alloc`` (warning) — unbounded allocation or serialization
  (``copy.deepcopy``, ``pickle.dumps``) in the handler path.

Reachability prunes call edges whose call site carries an inline
``# raydp: ignore[R2]`` — that is how a dual-use function documents
"this branch is not taken on the signal path" (e.g. a call guarded by
a ``signal_safe`` flag).
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Set

from raydp_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    call_name,
    classify_blocking,
    walk_no_nested,
)
from raydp_tpu.analysis.core import Finding, ModuleInfo, Project

RULE = "R2"

_SIGNAL_CONSTANTS = {"SIG_DFL", "SIG_IGN"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error",
                "exception", "critical", "log"}
_LOG_RECEIVERS = ("log", "logger", "logging")
_ALLOC_CALLS = {"copy.deepcopy", "deepcopy", "pickle.dumps",
                "pickle.dump", "marshal.dumps"}


def _handler_roots(project: Project, graph: CallGraph) -> Dict[str, ast.Call]:
    """Resolved handler qualname -> the registering ``signal.signal``
    call (for diagnostics on unresolvable handlers)."""
    roots: Dict[str, ast.Call] = {}
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node.func)
            if not (name == "signal.signal" or name.endswith(".signal")
                    or name == "signal"):
                continue
            if len(node.args) < 2:
                continue
            handler = node.args[1]
            hname = call_name(handler)
            if not hname or hname.split(".")[-1] in _SIGNAL_CONSTANTS:
                continue
            fn = graph.enclosing_function(mod, node.lineno)
            resolved = _resolve_ref(graph, mod, fn, hname)
            if resolved:
                roots[resolved] = node
    return roots


def _resolve_ref(graph: CallGraph, mod: ModuleInfo,
                 fn: Optional[FunctionInfo], dotted: str) -> Optional[str]:
    """Resolve a bare function reference (not a call) to a project
    function qualname."""
    if dotted.startswith("self.") and fn is not None and fn.cls:
        cand = f"{fn.cls}.{dotted[len('self.'):]}"
        if cand in graph.functions:
            return cand
    resolved = graph._resolve_dotted(mod, dotted)
    if resolved in graph.functions:
        return resolved
    # method on a known class (e.g. `recorder._sigterm_handler` where
    # the instance table resolved the class already)
    if "." in resolved:
        base, meth = resolved.rsplit(".", 1)
        if base in graph.classes and f"{base}.{meth}" in graph.functions:
            return f"{base}.{meth}"
    last = dotted.rsplit(".", 1)[-1]
    matches = graph._methods_by_name.get(last, [])
    if len(matches) == 1:
        return matches[0]
    cand = f"{mod.name}.{last}"
    if cand in graph.functions:
        return cand
    return None


def _r2_reachable(graph: CallGraph, roots) -> Dict[str, List[str]]:
    """BFS like CallGraph.reachable, but skips call edges whose source
    line carries an R2 suppression — the escape hatch for dual-use
    functions with a signal-safe branch."""
    chains: Dict[str, List[str]] = {}
    dq = deque()
    for r in roots:
        if r in graph.functions:
            chains[r] = [r]
            dq.append((r, 0))
    while dq:
        cur, depth = dq.popleft()
        if depth >= 12:
            continue
        fn = graph.functions[cur]
        for call, target in fn.calls:
            if not target or target in chains:
                continue
            if _edge_suppressed(fn.module, call.lineno):
                continue
            chains[target] = chains[cur] + [target]
            dq.append((target, depth + 1))
    return chains


def _edge_suppressed(mod: ModuleInfo, lineno: int) -> bool:
    lines = [lineno]
    above = lineno - 1
    while above >= 1 and mod.source_at(above).lstrip().startswith("#"):
        lines.append(above)
        above -= 1
    for line in lines:
        tokens = mod.suppressions.get(line)
        if tokens and ("all" in tokens or RULE in tokens):
            return True
    return False


def check(project: Project) -> List[Finding]:
    graph: CallGraph = project.graph
    roots = _handler_roots(project, graph)
    if not roots:
        return []
    chains = _r2_reachable(graph, roots)
    findings: List[Finding] = []
    for qual in sorted(chains):
        fn = graph.functions[qual]
        via = " -> ".join(q.rsplit(".", 1)[-1] for q in chains[qual])
        _scan_function(fn, graph, via, findings)
    return findings


def _scan_function(fn: FunctionInfo, graph: CallGraph, via: str,
                   findings: List[Finding]) -> None:
    mod = fn.module
    if isinstance(fn.node, ast.Lambda):
        nodes = list(walk_no_nested(fn.node.body))
    else:
        nodes = []
        for stmt in fn.node.body:
            nodes.extend(walk_no_nested(stmt))
    for node in nodes:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                dotted = call_name(item.context_expr)
                if dotted and _looks_locky(dotted):
                    findings.append(_mk(
                        "signal-unsafe-lock", "error", mod, node,
                        f"`with {dotted}:` reachable from signal handler "
                        f"({via}); a handler interrupting the holder "
                        f"deadlocks — use try-acquire", fn))
            continue
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node.func)
        resolved_ext = graph.resolved_external(fn, node)
        label = classify_blocking(node, resolved_ext)
        if label is not None:
            if label.startswith("lock acquire"):
                findings.append(_mk(
                    "signal-unsafe-lock", "error", mod, node,
                    f"blocking {name}() reachable from signal handler "
                    f"({via}); pass blocking=False and degrade "
                    f"gracefully", fn))
            else:
                findings.append(_mk(
                    "signal-unsafe-blocking", "error", mod, node,
                    f"{label} reachable from signal handler ({via})",
                    fn))
            continue
        if _is_logging(node, name):
            findings.append(_mk(
                "signal-unsafe-logging", "error", mod, node,
                f"logging call {name}() reachable from signal handler "
                f"({via}); the logging module takes an internal lock",
                fn))
            continue
        for alloc in _ALLOC_CALLS:
            if name == alloc or resolved_ext == alloc:
                findings.append(_mk(
                    "signal-alloc", "warning", mod, node,
                    f"unbounded allocation {name}() reachable from "
                    f"signal handler ({via}); keep handlers O(1)", fn))
                break


def _looks_locky(dotted: str) -> bool:
    last = dotted.rsplit(".", 1)[-1].lower()
    return (last == "_mu" or "lock" in last or "mutex" in last
            or last.endswith("_cv"))


def _is_logging(node: ast.Call, name: str) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in _LOG_METHODS:
        return False
    recv = call_name(node.func.value).rsplit(".", 1)[-1].lower()
    return any(recv == r or recv.endswith(r) for r in _LOG_RECEIVERS)


def _mk(name: str, severity: str, mod: ModuleInfo, node: ast.AST,
        message: str, fn: FunctionInfo) -> Finding:
    return Finding(
        rule=RULE, name=name, severity=severity, path=mod.rel,
        line=node.lineno, col=getattr(node, "col_offset", 0),
        message=message, scope=fn.qualname,
    )
