"""R4 telemetry consistency.

Three string-keyed contracts hold the telemetry plane together, and
none of them were machine-checked before this rule:

* **metric routing** — ``metrics.counter_add("x/y", ...)`` names are
  routed to dedicated Prometheus families by literal comparisons in
  ``telemetry/export.py``; anything unrouted silently lands in the
  generic ``raydp_counter_total``/``raydp_gauge``/``raydp_histogram``
  fallbacks. An emitted name must therefore be routed **or**
  documented (so the generic-family landing is a recorded decision).
  → ``unrouted-metric`` (error)
* **family docs** — every family registered via ``_Family(name, ...)``
  must appear in the docs. → ``undocumented-family`` (error)
* **env vars** — every ``RAYDP_TPU_*`` variable read in code must
  appear in the docs table. → ``undocumented-env`` (error)
* **job attribution** — the ``usage/*`` and ``job/*`` counter
  namespaces are the job accounting ledger; they are only coherent
  when both halves (the cluster-global ``usage/<kind>`` counter and
  the per-job ``job/<id>/<kind>`` counter) are emitted together, which
  is exactly what ``accounting.add_usage`` does. A raw
  ``metrics.counter_add("usage/...", ...)`` anywhere outside
  ``telemetry/accounting.py`` bypasses the ledger and silently loses
  the per-job attribution. → ``unattributed-metric`` (error)

Name resolution follows module-level string constants (e.g.
``STALL_COUNTER = "watchdog/stalls"`` used as ``counter_add(STALL_COUNTER)``),
including across modules via imports. f-string names are checked by
their static prefix against routed prefixes; fully dynamic names are
skipped (under-approximate, never noisy).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.analysis.callgraph import CallGraph, call_name
from raydp_tpu.analysis.core import Finding, ModuleInfo, Project

RULE = "R4"

_EMIT_METHODS = {"counter_add", "gauge_set", "gauge_max", "histogram",
                 "timer", "meter"}
_ENV_PREFIX = "RAYDP_TPU_"

# The job accounting ledger's namespaces: raw emits into these outside
# the accounting module lose per-job attribution (use add_usage).
_LEDGER_PREFIXES = ("usage/", "job/")
_LEDGER_HOME = "telemetry/accounting.py"


def _module_constants(project: Project) -> Dict[str, str]:
    """``module.NAME`` -> string value, for top-level str assignments."""
    out: Dict[str, str] = {}
    for mod in project.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[f"{mod.name}.{t.id}"] = node.value.value
    return out


def _resolve_str(expr: ast.AST, mod: ModuleInfo, graph: CallGraph,
                 consts: Dict[str, str]) -> Tuple[Optional[str], bool]:
    """(value, is_prefix_only). Constants resolve exactly; f-strings
    resolve to their static prefix with is_prefix_only=True; everything
    else is (None, False)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value, False
    if isinstance(expr, (ast.Name, ast.Attribute)):
        dotted = call_name(expr)
        if dotted:
            resolved = graph._resolve_dotted(mod, dotted)
            if resolved in consts:
                return consts[resolved], False
            if "." not in dotted and f"{mod.name}.{dotted}" in consts:
                return consts[f"{mod.name}.{dotted}"], False
    if isinstance(expr, ast.JoinedStr):
        prefix = ""
        for part in expr.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return (prefix, True) if prefix else (None, False)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left, lp = _resolve_str(expr.left, mod, graph, consts)
        if left is not None and not lp:
            right, rp = _resolve_str(expr.right, mod, graph, consts)
            if right is not None and not rp:
                return left + right, False
            return left, True
    return None, False


def _export_module(project: Project) -> Optional[ModuleInfo]:
    mod = project.module_endswith("telemetry/export.py")
    if mod is not None:
        return mod
    # fixture fallback: any module that registers _Family instances
    for m in project.modules.values():
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node.func).rsplit(".", 1)[-1] == "_Family":
                return m
    return None


def _routing(mod: ModuleInfo) -> Tuple[Set[str], Set[str], Set[str]]:
    """(family_names, routed_exact, routed_prefixes) from the export
    module: ``_Family("name", ...)`` first args, string literals used
    in ``==``/``in`` comparisons, and ``.startswith("p")`` prefixes."""
    families: Set[str] = set()
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            fname = call_name(node.func).rsplit(".", 1)[-1]
            if fname == "_Family" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                families.add(node.args[0].value)
            elif fname == "startswith":
                for a in node.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        prefixes.add(a.value)
        elif isinstance(node, ast.Compare):
            ops = node.ops
            if not any(isinstance(o, (ast.Eq, ast.In)) for o in ops):
                continue
            for sub in [node.left] + node.comparators:
                for c in ast.walk(sub):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str) and c.value:
                        exact.add(c.value)
    return families, exact, prefixes


def _doc_text(project: Project) -> str:
    return "\n".join(project.docs.values())


def check(project: Project) -> List[Finding]:
    graph: CallGraph = project.graph
    consts = _module_constants(project)
    docs = _doc_text(project)
    findings: List[Finding] = []

    export_mod = _export_module(project)
    families: Set[str] = set()
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    if export_mod is not None:
        families, exact, prefixes = _routing(export_mod)

    # 1. emitted metric names must be routed or documented
    seen_metrics: Set[Tuple[str, str, int]] = set()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _EMIT_METHODS or not node.args:
                continue
            value, prefix_only = _resolve_str(
                node.args[0], mod, graph, consts)
            if value is None:
                continue  # fully dynamic — out of scope
            if _ledger_name(value) and not mod.rel.endswith(_LEDGER_HOME):
                key = (mod.rel, value, node.lineno)
                if key in seen_metrics:
                    continue
                seen_metrics.add(key)
                findings.append(Finding(
                    rule=RULE, name="unattributed-metric",
                    severity="error",
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    message=f"raw emit of ledger metric '{value}' "
                            f"bypasses job attribution; use "
                            f"accounting.add_usage so the per-job "
                            f"counter is billed alongside the "
                            f"cluster-global one",
                    scope="",
                ))
                continue
            if _routed(value, prefix_only, exact, prefixes):
                continue
            if not prefix_only and value in docs:
                continue
            if prefix_only and value in docs:
                continue
            key = (mod.rel, value, node.lineno)
            if key in seen_metrics:
                continue
            seen_metrics.add(key)
            kind = "name prefix" if prefix_only else "name"
            findings.append(Finding(
                rule=RULE, name="unrouted-metric", severity="error",
                path=mod.rel, line=node.lineno, col=node.col_offset,
                message=f"metric {kind} '{value}' has no dedicated "
                        f"family route in telemetry/export.py and is "
                        f"not documented; it will land in the generic "
                        f"fallback family unannounced",
                scope="",
            ))

    # 2. every registered family must be documented
    if export_mod is not None:
        for fam in sorted(families):
            if fam not in docs:
                findings.append(Finding(
                    rule=RULE, name="undocumented-family",
                    severity="error",
                    path=export_mod.rel, line=1, col=0,
                    message=f"Prometheus family '{fam}' is registered "
                            f"in {export_mod.rel} but never mentioned "
                            f"in the docs",
                    scope="",
                ))

    # 3. every RAYDP_TPU_* env var read must be documented
    env_sites: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            var = _env_read(node, mod, graph, consts)
            if var and var.startswith(_ENV_PREFIX):
                env_sites.setdefault(var, (mod.rel, node.lineno))
        # constants that *look like* env names count as reads too when
        # passed around (covered above via resolution); nothing extra.
    for var in sorted(env_sites):
        if var not in docs:
            rel, line = env_sites[var]
            findings.append(Finding(
                rule=RULE, name="undocumented-env", severity="error",
                path=rel, line=line, col=0,
                message=f"env var '{var}' is read here but absent from "
                        f"the docs (add it to doc/configuration.md)",
                scope="",
            ))
    return findings


def _ledger_name(value: str) -> bool:
    return any(value.startswith(p) for p in _LEDGER_PREFIXES)


def _routed(value: str, prefix_only: bool, exact: Set[str],
            prefixes: Set[str]) -> bool:
    if not prefix_only and value in exact:
        return True
    for p in prefixes:
        if value.startswith(p) or (prefix_only and p.startswith(value)):
            return True
    return False


def _env_read(node: ast.AST, mod: ModuleInfo, graph: CallGraph,
              consts: Dict[str, str]) -> Optional[str]:
    """The env-var name if ``node`` reads one: ``os.environ.get(K)``,
    ``os.environ[K]``, ``os.getenv(K)`` — K literal or constant."""
    key_expr = None
    if isinstance(node, ast.Call):
        name = call_name(node.func)
        last = name.rsplit(".", 1)[-1] if name else ""
        if (name.endswith("environ.get") or last == "getenv") and node.args:
            key_expr = node.args[0]
    elif isinstance(node, ast.Subscript):
        base = call_name(node.value)
        if base.endswith("environ"):
            key_expr = node.slice
    if key_expr is None:
        return None
    value, prefix_only = _resolve_str(key_expr, mod, graph, consts)
    if value is None or prefix_only:
        return None
    return value
