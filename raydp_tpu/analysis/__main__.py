"""CLI: ``python -m raydp_tpu.analysis [paths]``.

Exit codes: 0 clean (or everything baselined), 1 active findings,
2 usage/internal error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from raydp_tpu.analysis import baseline as baseline_mod
from raydp_tpu.analysis.core import RULES, run_analysis


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raydp_tpu.analysis",
        description="raydpcheck: framework-aware static analysis "
                    "(rules R1-R5; see doc/analysis.md)",
    )
    p.add_argument("paths", nargs="*", default=["raydp_tpu"],
                   help="files/directories to analyze "
                        "(default: raydp_tpu)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run "
                        f"(default: all of {','.join(sorted(RULES))})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the JSON report to stdout instead of "
                        "human output")
    p.add_argument("--json-out", default=None, metavar="FILE",
                   help="also write the JSON report to FILE")
    p.add_argument("--root", default=None,
                   help="repo root override (docs + baseline live here; "
                        "auto-detected from the scanned packages)")
    p.add_argument("--docs-dir", default=None,
                   help="docs directory override for the R4 parity "
                        "checks (default: <root>/doc plus README.md)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: "
                        "<root>/analysis-baseline.json if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"error: unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(RULES))})", file=sys.stderr)
            return 2

    # First pass without a baseline to discover the root, then load the
    # baseline relative to it. Cheap enough (<1s) to keep the CLI simple
    # would be ideal, but one pass suffices: detect root up front.
    from raydp_tpu.analysis.core import _find_root, _iter_py_files

    files = _iter_py_files(args.paths)
    if not files:
        print(f"error: no Python files under: {' '.join(args.paths)}",
              file=sys.stderr)
        return 2
    root = _find_root(files, args.root)

    baseline_path = args.baseline or baseline_mod.default_path(root)
    baseline_doc = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline_doc = baseline_mod.load(baseline_path)
        except (ValueError, OSError, json.JSONDecodeError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2

    result = run_analysis(
        args.paths, rules=rules, root=root, docs_dir=args.docs_dir,
        baseline=baseline_doc,
    )

    if args.write_baseline:
        baseline_mod.write(baseline_path, result.findings)
        print(f"baseline: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    report = result.to_dict()
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")

    if args.as_json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for f in result.findings:
            print(f.render())
        parts = [
            f"{len(result.findings)} finding(s)",
            f"{result.files} file(s)",
            f"{result.seconds:.2f}s",
        ]
        if result.suppressed:
            parts.append(f"{result.suppressed} suppressed")
        if result.baselined:
            parts.append(f"{result.baselined} baselined")
        print("raydpcheck: " + ", ".join(parts))
        if result.stale_baseline:
            print(f"raydpcheck: {len(result.stale_baseline)} stale "
                  f"baseline entr(y/ies) no longer fire — ratchet down "
                  f"by removing them from {baseline_path}:")
            for fp in result.stale_baseline:
                print(f"  stale: {fp}")

    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
