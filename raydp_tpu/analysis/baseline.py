"""Ratcheting baseline: accepted pre-existing findings.

The baseline file (default ``<repo>/analysis-baseline.json``) maps
finding fingerprints to a short record of what was accepted. Runs
subtract baselined findings from the active set, so the repo gates on
*new* debt only, and report **stale** entries (baselined findings that
no longer fire) so the file only ever shrinks — the ratchet.

Fingerprints hash the rule, path, scope, check name and a
digit-stripped message slug — not line numbers — so unrelated edits
don't churn the file.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from raydp_tpu.analysis.core import Finding

__all__ = ["default_path", "load", "write"]

DEFAULT_NAME = "analysis-baseline.json"


def default_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_NAME)


def load(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a baseline file "
                         f"(missing 'findings' mapping)")
    return doc


def write(path: str, findings: List[Finding]) -> Dict[str, Any]:
    doc = {
        "version": 1,
        "comment": "Accepted pre-existing raydpcheck findings. Entries "
                   "are removed (never added back) as debt is paid "
                   "down — see doc/analysis.md for the workflow.",
        "findings": {
            f.fingerprint: {
                "rule": f.rule,
                "name": f.name,
                "path": f.path,
                "message": f.message,
            }
            for f in findings
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return doc
