"""raydpcheck: framework-aware static analysis for raydp_tpu.

An AST-based rule engine tuned to the concurrency and telemetry idioms
of THIS codebase — not a general linter. Every rule is grounded in a
bug class the repo has already shipped and fixed by hand (see
``doc/analysis.md`` for the catalogue and the history behind each):

* **R1 lock-discipline** — lock-order inversions and locks held across
  blocking calls (RPC send/recv, ``queue.get``, ``time.sleep``,
  ``subprocess``, ``future.result()``), built from a per-module
  lock-acquisition graph (the ``SPMDJob._rank_health`` class of race).
* **R2 signal-safety** — the call graph reachable from registered
  signal handlers must not acquire locks, log, or do unbounded
  allocation (the PR 3 SIGTERM-deadlock class).
* **R3 RPC-handler discipline** — handlers wired into :class:`RpcServer`
  that (transitively) block must either be registered in the
  long-stall set (``_LONG_HANDLER_METHODS``) or bracket the blocking
  region with their own ``inflight()`` override.
* **R4 telemetry consistency** — metric names must route to a
  registered Prometheus family in ``telemetry/export.py`` or be
  documented; every family and every ``RAYDP_TPU_*`` env var read in
  code must appear in the docs.
* **R5 JAX hazards** — host-device syncs inside jitted bodies and
  step loops, and train-step jits missing ``donate_argnums``.

Run it as ``python -m raydp_tpu.analysis [paths]``. Findings can be
suppressed inline with ``# raydp: ignore[R1]`` (rule id or rule name)
on the offending line or the line above, or accepted wholesale into a
ratcheting baseline file (``--write-baseline``) so pre-existing debt
never regresses while new code ships clean.
"""
from raydp_tpu.analysis.core import (  # noqa: F401
    AnalysisResult,
    Finding,
    run_analysis,
)

__all__ = ["Finding", "AnalysisResult", "run_analysis"]
