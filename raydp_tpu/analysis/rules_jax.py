"""R5 JAX hazards.

Host↔device synchronization inside hot code is the quiet MFU killer on
TPU: a single ``.item()`` in a step loop serializes the pipelined
dispatch queue, and a train-step ``jit`` without buffer donation
doubles parameter HBM. Checks:

* ``host-sync-in-jit`` (error) — ``.item()``, ``float(x)``/``int(x)``
  on non-literals, ``np.asarray``/``np.array``, and
  ``.block_until_ready()`` inside a jit-compiled function body (these
  either fail under tracing or silently force a sync).
* ``device-put-in-jit`` (error) — ``jax.device_put`` inside a jitted
  body (placement belongs outside the traced region).
* ``host-sync-in-step-loop`` (warning) — per-iteration ``.item()`` /
  ``block_until_ready()`` / ``device_put`` inside a training step
  loop (a ``for``/``while`` in a function whose name mentions
  train/fit/epoch/step). Profiling helpers are exempt: syncing before
  reading a timer is the one legitimate use.
* ``jit-missing-donation`` (warning) — a ``jax.jit(...)`` whose
  target name contains ``step`` or ``update`` with no
  ``donate_argnums``/``donate_argnames``.
* ``host-sync-in-decode-loop`` (warning) — per-token ``.item()`` /
  ``block_until_ready()`` / ``device_get`` inside an autoregressive
  decode loop (a ``for``/``while`` in a function whose name mentions
  decode/generate/run_round). Decode rounds must fetch the whole
  batch's tokens in ONE host sync per round — a per-token sync
  serializes the round loop exactly like a per-step ``.item()``
  serializes training, but at token frequency.

Jitted functions are found via decorators (``@jax.jit``, ``@jit``,
``@partial(jax.jit, ...)``) and wrapper assignments
(``f = jax.jit(g)``).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    call_name,
    walk_no_nested,
)
from raydp_tpu.analysis.core import Finding, ModuleInfo, Project

RULE = "R5"

_LOOPY_FN_HINTS = ("train", "fit", "epoch", "step_loop", "run_steps")
_DECODE_FN_HINTS = ("decode", "generate", "run_round", "token_loop")
_PROFILING_HINTS = ("profil", "bench", "timing", "measure", "trace",
                    "warmup")
_DONATE_TARGET_HINTS = ("step", "update")


def _is_jit_name(dotted: str) -> bool:
    last = dotted.rsplit(".", 1)[-1]
    return last == "jit" or last == "pjit"


def _jit_decorated(fn: FunctionInfo) -> bool:
    node = fn.node
    for dec in getattr(node, "decorator_list", []):
        if isinstance(dec, ast.Call):
            name = call_name(dec.func)
            if _is_jit_name(name):
                return True
            if name.rsplit(".", 1)[-1] == "partial" and dec.args:
                inner = call_name(dec.args[0])
                if _is_jit_name(inner):
                    return True
        else:
            if _is_jit_name(call_name(dec)):
                return True
    return False


def _jit_wrapped(project: Project, graph: CallGraph) -> Set[str]:
    """Functions passed to ``jax.jit(...)`` as the first argument
    anywhere in the project → their qualnames."""
    from raydp_tpu.analysis.rules_signals import _resolve_ref

    out: Set[str] = set()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not _is_jit_name(call_name(node.func)):
                continue
            dotted = call_name(node.args[0])
            if not dotted:
                continue
            fn = graph.enclosing_function(mod, node.lineno)
            target = _resolve_ref(graph, mod, fn, dotted)
            if target:
                out.add(target)
    return out


def _profiling_context(fn: FunctionInfo) -> bool:
    text = (fn.qualname + " " + fn.module.rel).lower()
    return any(h in text for h in _PROFILING_HINTS)


def check(project: Project) -> List[Finding]:
    graph: CallGraph = project.graph
    findings: List[Finding] = []
    wrapped = _jit_wrapped(project, graph)

    for qual, fn in graph.functions.items():
        if isinstance(fn.node, ast.Lambda):
            continue
        if _jit_decorated(fn) or qual in wrapped:
            _scan_jit_body(fn, findings)
        if any(h in fn.node.name.lower() for h in _LOOPY_FN_HINTS) and \
                not _profiling_context(fn):
            _scan_step_loops(fn, findings)
        if any(h in fn.node.name.lower() for h in _DECODE_FN_HINTS) and \
                not _profiling_context(fn) and \
                "reference" not in fn.node.name.lower():
            _scan_decode_loops(fn, findings)

    _check_donation(project, graph, findings)
    return findings


def _iter_calls(stmts):
    for stmt in stmts:
        for node in walk_no_nested(stmt):
            if isinstance(node, ast.Call):
                yield node


def _scan_jit_body(fn: FunctionInfo, findings: List[Finding]) -> None:
    mod = fn.module
    for node in _iter_calls(fn.node.body):
        name = call_name(node.func)
        last = name.rsplit(".", 1)[-1] if name else ""
        msg = None
        rname = "host-sync-in-jit"
        if isinstance(node.func, ast.Attribute) and last == "item" \
                and not node.args:
            msg = "`.item()` inside a jitted body forces a host sync " \
                  "(and fails under tracing)"
        elif last in ("float", "int") and "." not in name and \
                len(node.args) == 1 and \
                not isinstance(node.args[0], ast.Constant):
            msg = f"`{last}()` on a traced value inside a jitted body " \
                  f"forces a host sync"
        elif name in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array", "onp.asarray", "onp.array"):
            msg = f"`{name}()` inside a jitted body pulls the value " \
                  f"to host"
        elif last == "block_until_ready":
            msg = "`block_until_ready()` inside a jitted body is a " \
                  "host sync"
        elif last == "device_put":
            msg = "`device_put` inside a jitted body; placement " \
                  "belongs outside the traced region"
            rname = "device-put-in-jit"
        if msg:
            findings.append(Finding(
                rule=RULE, name=rname, severity="error",
                path=mod.rel, line=node.lineno, col=node.col_offset,
                message=msg, scope=fn.qualname,
            ))


def _scan_step_loops(fn: FunctionInfo, findings: List[Finding]) -> None:
    mod = fn.module
    seen: Set[Tuple[int, int]] = set()
    for stmt in ast.walk(fn.node):
        if not isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in _iter_calls(stmt.body):
            if (node.lineno, node.col_offset) in seen:
                continue  # nested loops walk the same body twice
            seen.add((node.lineno, node.col_offset))
            name = call_name(node.func)
            last = name.rsplit(".", 1)[-1] if name else ""
            msg = None
            if isinstance(node.func, ast.Attribute) and last == "item" \
                    and not node.args:
                msg = "`.item()` every iteration serializes dispatch; " \
                      "accumulate on device and sync once per log " \
                      "interval"
            elif last == "block_until_ready":
                msg = "`block_until_ready()` every iteration defeats " \
                      "async dispatch (fine in profiling code only)"
            elif last == "device_put":
                msg = "`device_put` inside the step loop; stage inputs " \
                      "ahead (prefetch) instead"
            if msg:
                findings.append(Finding(
                    rule=RULE, name="host-sync-in-step-loop",
                    severity="warning",
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    message=msg, scope=fn.qualname,
                ))


def _scan_decode_loops(fn: FunctionInfo, findings: List[Finding]) -> None:
    """Per-token host syncs inside an autoregressive decode loop.

    Reference implementations are exempt at the call site (a
    ``reference_*`` decode is *supposed* to be the slow unbatched
    path); everything else named like a decode/generate loop must
    batch its token fetch — one sync per round, never one per token
    or per sequence."""
    mod = fn.module
    seen: Set[Tuple[int, int]] = set()
    for stmt in ast.walk(fn.node):
        if not isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in _iter_calls(stmt.body):
            if (node.lineno, node.col_offset) in seen:
                continue  # nested loops walk the same body twice
            seen.add((node.lineno, node.col_offset))
            name = call_name(node.func)
            last = name.rsplit(".", 1)[-1] if name else ""
            msg = None
            if isinstance(node.func, ast.Attribute) and last == "item" \
                    and not node.args:
                msg = "`.item()` per token serializes the decode " \
                      "round; fetch the whole batch's tokens in one " \
                      "device_get per round"
            elif last == "block_until_ready":
                msg = "`block_until_ready()` per token stalls the " \
                      "decode round loop; the per-round token fetch " \
                      "is the only sync needed"
            elif last == "device_get":
                msg = "`device_get` inside the per-token loop; hoist " \
                      "it to one batched fetch per decode round"
            if msg:
                findings.append(Finding(
                    rule=RULE, name="host-sync-in-decode-loop",
                    severity="warning",
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    message=msg, scope=fn.qualname,
                ))


def _check_donation(project: Project, graph: CallGraph,
                    findings: List[Finding]) -> None:
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not _is_jit_name(call_name(node.func)):
                continue
            target = call_name(node.args[0])
            last = target.rsplit(".", 1)[-1].lower() if target else ""
            # only train/update steps benefit — donating into eval or
            # predict steps would destroy the params they borrow
            if "train" not in last or \
                    not any(h in last for h in _DONATE_TARGET_HINTS):
                continue
            kws = {kw.arg for kw in node.keywords}
            if kws & {"donate_argnums", "donate_argnames"}:
                continue
            fn = graph.enclosing_function(mod, node.lineno)
            findings.append(Finding(
                rule=RULE, name="jit-missing-donation", severity="warning",
                path=mod.rel, line=node.lineno, col=node.col_offset,
                message=f"jit of '{target}' without donate_argnums; "
                        f"train-step params/opt-state should be donated "
                        f"to halve HBM for the update",
                scope=fn.qualname if fn else "",
            ))
