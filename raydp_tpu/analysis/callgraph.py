"""Project call graph + blocking-call classifier.

Builds a conservative, alias-aware call graph over every parsed module:

* a **function index** mapping qualnames (``pkg.mod.Class.method`` /
  ``pkg.mod.func``) to their AST nodes;
* per-module **import alias** tables (``from x import y as a`` →
  ``a`` resolves to ``x.y``), including function-level imports;
* **module-level instances** (``recorder = FlightRecorder()``) so
  ``recorder.record(...)`` resolves to ``FlightRecorder.record``;
* ``self.m()`` resolution to the enclosing class's method.

Resolution is best-effort and intentionally under-approximate (unknown
calls resolve to nothing rather than everything); the rules that walk
it (R2 signal-safety, R3 handler discipline) compensate by also
classifying *direct* blocking evidence syntactically.
"""
from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raydp_tpu.analysis.core import ModuleInfo, Project

__all__ = [
    "FunctionInfo",
    "CallGraph",
    "classify_blocking",
    "call_name",
    "qual_last",
    "walk_no_nested",
]


def call_name(node: ast.AST) -> str:
    """Dotted source text of a call target: ``a.b.c`` for
    ``a.b.c(...)``; empty string for computed targets."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        # chained call like FlightRecorder().record — keep the attrs only
        pass
    elif parts:
        # computed base (subscript etc.) — keep attribute tail
        pass
    else:
        return ""
    return ".".join(reversed(parts))


def qual_last(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def walk_no_nested(node: ast.AST):
    """Yield ``node`` and descendants without descending into nested
    function/class definitions — calls in a closure belong to the
    closure's own :class:`FunctionInfo`, not its parent's."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield from walk_no_nested(child)


# -- blocking-call classifier -------------------------------------------

# Dotted-suffix matches on the *resolved or source* call name.
_BLOCKING_SUFFIXES = (
    "time.sleep",
    "sleep",  # bare `sleep(...)` after `from time import sleep`
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "socket.create_connection",
)

# Method names that block regardless of receiver type.
_BLOCKING_METHODS = {"result", "communicate", "recv", "recv_bytes", "send_bytes"}

# RPC idioms in this repo: RpcClient.call / try_call, shipping senders.
_RPC_METHODS = {"call", "try_call"}


def _is_queue_receiver(recv: str) -> bool:
    last = qual_last(recv).lower()
    return last == "q" or last.endswith("_q") or "queue" in last


def classify_blocking(node: ast.Call, resolved: Optional[str] = None) -> Optional[str]:
    """Return a human label if ``node`` is a blocking call, else None.

    ``resolved`` is the project-resolved dotted name when the call graph
    could resolve the target (e.g. ``subprocess.run`` for an aliased
    import); the syntactic name is always checked too.
    """
    src = call_name(node.func)
    names = [n for n in (resolved, src) if n]
    for name in names:
        for suf in _BLOCKING_SUFFIXES:
            if name == suf or name.endswith("." + suf):
                return f"blocking call {name}()"
    if not isinstance(node.func, ast.Attribute):
        return None
    meth = node.func.attr
    recv = call_name(node.func.value)
    if meth in _BLOCKING_METHODS:
        return f"blocking {recv or '<expr>'}.{meth}()"
    if meth in _RPC_METHODS:
        return f"RPC {recv or '<expr>'}.{meth}()"
    if meth == "get" and _is_queue_receiver(recv):
        return f"blocking queue get {recv}.get()"
    if meth == "wait":
        # Event.wait()/Condition.wait() — any receiver; `wait(0)` with a
        # constant-zero timeout is a poll, not a block.
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Constant) and a.value == 0:
                return None
        return f"blocking {recv or '<expr>'}.wait()"
    if meth == "join" and not node.args:
        # thread/process join; `sep.join(iterable)` always has an arg.
        return f"blocking {recv or '<expr>'}.join()"
    if meth == "acquire":
        for kw in node.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is False:
            return None
        return f"lock acquire {recv or '<expr>'}.acquire()"
    return None


# -- function index + call graph ----------------------------------------


@dataclass
class FunctionInfo:
    qualname: str  # module.Class.method or module.func
    module: ModuleInfo
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    cls: Optional[str] = None  # enclosing class qualname (module.Class)
    calls: List[Tuple[ast.Call, str]] = field(default_factory=list)
    # resolved callee qualnames (filled by CallGraph)
    callees: Set[str] = field(default_factory=set)


class _Indexer(ast.NodeVisitor):
    """Collects functions, import aliases, and module-level instances
    for one module."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.functions: Dict[str, FunctionInfo] = {}
        self.aliases: Dict[str, str] = {}  # local name -> dotted target
        self.instances: Dict[str, str] = {}  # var name -> class dotted name
        self.classes: Dict[str, List[str]] = {}  # class qual -> base names
        self._stack: List[str] = [mod.name]
        self._cls_stack: List[str] = []

    # imports ----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = \
                a.name if a.asname else a.name.split(".")[0]
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            # relative import: resolve against this module's package
            pkg = self.mod.name.split(".")
            # drop the module segment itself plus (level-1) packages
            pkg = pkg[: len(pkg) - node.level]
            base = ".".join(pkg + ([node.module] if node.module else []))
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
        self.generic_visit(node)

    # definitions ------------------------------------------------------
    def _qual(self, name: str) -> str:
        return ".".join(self._stack + [name])

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        qual = self._qual(node.name)
        self.classes[qual] = [call_name(b) for b in node.bases]
        self._stack.append(node.name)
        self._cls_stack.append(qual)
        self.generic_visit(node)
        self._cls_stack.pop()
        self._stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        cls = self._cls_stack[-1] if self._cls_stack else None
        self.functions[qual] = FunctionInfo(
            qualname=qual, module=self.mod, node=node, cls=cls)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # module-level instances -------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        if len(self._stack) == 1 and isinstance(node.value, ast.Call):
            ctor = call_name(node.value.func)
            if ctor and ctor[:1].isupper() or "." in ctor:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.instances[t.id] = ctor
        self.generic_visit(node)


class CallGraph:
    """Whole-project function index with best-effort call resolution."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}  # module -> alias table
        self.instances: Dict[str, Dict[str, str]] = {}
        self.classes: Dict[str, List[str]] = {}
        self._methods_by_name: Dict[str, List[str]] = {}

        for mod in project.modules.values():
            ix = _Indexer(mod)
            ix.visit(mod.tree)
            self.functions.update(ix.functions)
            self.aliases[mod.name] = ix.aliases
            self.instances[mod.name] = ix.instances
            self.classes.update(ix.classes)

        for qual, fn in self.functions.items():
            if fn.cls:
                self._methods_by_name.setdefault(
                    qual.rsplit(".", 1)[-1], []).append(qual)

        for fn in self.functions.values():
            self._link(fn)

    # -- resolution ----------------------------------------------------

    def _resolve_dotted(self, mod: ModuleInfo, dotted: str) -> str:
        """Expand the leading segment through the module's alias and
        instance tables; returns a project-absolute dotted name (may
        still refer to something external)."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        table = self.aliases.get(mod.name, {})
        inst = self.instances.get(mod.name, {})
        if head in inst:
            cls = self._resolve_dotted(mod, inst[head])
            return f"{cls}.{rest}" if rest else cls
        if head in table:
            head = table[head]
        elif f"{mod.name}.{head}" in self.functions or \
                f"{mod.name}.{head}" in self.classes:
            head = f"{mod.name}.{head}"
        return f"{head}.{rest}" if rest else head

    def resolve_call(self, fn: FunctionInfo, node: ast.Call) -> Optional[str]:
        """Resolve a call inside ``fn`` to a known function qualname,
        or None if the target is external/unknown."""
        dotted = call_name(node.func)
        if not dotted:
            return None
        mod = fn.module
        if dotted.startswith("self."):
            rest = dotted[len("self."):]
            if fn.cls:
                # direct method on the enclosing class (or single-class
                # fallback by method name)
                cand = f"{fn.cls}.{rest.split('.')[0]}"
                if cand in self.functions:
                    return cand
            first = rest.split(".")[0]
            matches = self._methods_by_name.get(first, [])
            if len(matches) == 1:
                return matches[0]
            return None
        resolved = self._resolve_dotted(mod, dotted)
        if resolved in self.functions:
            return resolved
        # instance method: Class.attr chains — `recorder.record` resolved
        # to pkg.mod.FlightRecorder.record above; also try trailing pair.
        if resolved in self.classes:
            return None
        # maybe Class().__init__ or classmethod via class name
        if "." in resolved:
            base, meth = resolved.rsplit(".", 1)
            if base in self.classes:
                cand = f"{base}.{meth}"
                if cand in self.functions:
                    return cand
        # cross-module instance: `metrics.snapshot()` after
        # `from pkg.utils.profiling import metrics` resolves through the
        # defining module's instance table to MetricsRegistry.snapshot.
        parts = resolved.split(".")
        for i in range(len(parts) - 1, 0, -1):
            owner_name = ".".join(parts[:i])
            owner = self.project.by_name.get(owner_name)
            if owner is None:
                continue
            rest = parts[i:]
            inst = self.instances.get(owner_name, {})
            if rest and rest[0] in inst:
                cls = self._resolve_dotted(owner, inst[rest[0]])
                cand = ".".join([cls] + rest[1:])
                if cand in self.functions:
                    return cand
            break
        # Deliberately NO unique-method-name fallback here: resolving
        # `os.path.join` to some project `join()` poisons reachability
        # with wildly wrong edges. Unknown attribute targets stay
        # unresolved (under-approximate).
        return None

    def resolved_external(self, fn: FunctionInfo, node: ast.Call) -> str:
        """The alias-expanded dotted name even when it's not a project
        function (used by the blocking classifier for aliased imports)."""
        return self._resolve_dotted(fn.module, call_name(node.func))

    def _link(self, fn: FunctionInfo) -> None:
        body = fn.node.body if not isinstance(fn.node, ast.Lambda) \
            else [fn.node.body]
        for stmt in body:
            for node in walk_no_nested(stmt):
                if isinstance(node, ast.Call):
                    target = self.resolve_call(fn, node)
                    fn.calls.append((node, target or ""))
                    if target:
                        fn.callees.add(target)

    # -- queries -------------------------------------------------------

    def function_at(self, mod: ModuleInfo, node: ast.AST) -> Optional[FunctionInfo]:
        for fn in self.functions.values():
            if fn.module is mod and fn.node is node:
                return fn
        return None

    def enclosing_function(self, mod: ModuleInfo, lineno: int) -> Optional[FunctionInfo]:
        best = None
        for fn in self.functions.values():
            if fn.module is not mod:
                continue
            n = fn.node
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= lineno <= end:
                if best is None or n.lineno > best.node.lineno:
                    best = fn
        return best

    def reachable(self, roots: Iterable[str], max_depth: int = 12) -> Dict[str, List[str]]:
        """BFS over the call graph. Returns reached qualname -> call
        chain (root..target) for diagnostics."""
        chains: Dict[str, List[str]] = {}
        dq = deque()
        for r in roots:
            if r in self.functions:
                chains[r] = [r]
                dq.append((r, 0))
        while dq:
            cur, depth = dq.popleft()
            if depth >= max_depth:
                continue
            for callee in self.functions[cur].callees:
                if callee not in chains:
                    chains[callee] = chains[cur] + [callee]
                    dq.append((callee, depth + 1))
        return chains
