"""R3 RPC-handler discipline.

:class:`RpcServer` auto-brackets every handler with
``inflight("rpc/<method>")`` so the watchdog can see stalls — but the
stall threshold is the *short* one unless the method name is in
``_LONG_HANDLER_METHODS``. A handler that legitimately blocks for
minutes (task execution, profile capture) therefore needs to be either

* registered in the long-stall set, or
* bracketed with its own ``inflight(...)`` region around the slow part
  (so the default bracket returns quickly).

This rule finds every handler table wired into an ``RpcServer(...)``
(dict literals, either inline or assigned to a local first), resolves
the handler functions, and walks each one (bounded depth) for blocking
work. Findings:

* ``blocking-handler-not-long`` (error) — handler transitively blocks
  but its method is not in ``_LONG_HANDLER_METHODS`` and its body has
  no ``inflight()`` bracket of its own. These are watchdog
  false-stall + SIGTERM-escalation candidates.
* ``stale-long-entry`` (warning) — a ``_LONG_HANDLER_METHODS`` entry
  that no scanned handler table registers (dead config).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    call_name,
    classify_blocking,
    walk_no_nested,
)
from raydp_tpu.analysis.core import Finding, ModuleInfo, Project

RULE = "R3"

_MAX_DEPTH = 6


def _long_methods(project: Project) -> Tuple[Set[str], Optional[Tuple[ModuleInfo, int]]]:
    """Parse ``_LONG_HANDLER_METHODS = frozenset({...})`` wherever it
    is defined (cluster/rpc.py in the real tree, any module in
    fixtures). Returns the set and its definition site."""
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "_LONG_HANDLER_METHODS" not in names:
                continue
            out: Set[str] = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
            return out, (mod, node.lineno)
    return set(), None


def _handler_tables(project: Project, graph: CallGraph):
    """Yield (module, method_name, handler_expr, lineno) for every
    entry of a handlers dict passed to an ``RpcServer(...)`` call."""
    for mod in project.modules.values():
        # dict literals assigned to names, per enclosing scope
        dicts_by_name: Dict[Tuple[Optional[str], str], ast.Dict] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
                fn = graph.enclosing_function(mod, node.lineno)
                scope = fn.qualname if fn else None
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        dicts_by_name[(scope, t.id)] = node.value
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            ctor = call_name(node.func)
            if not ctor or call_name(node.func).rsplit(".", 1)[-1] != "RpcServer":
                continue
            fn = graph.enclosing_function(mod, node.lineno)
            scope = fn.qualname if fn else None
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                d: Optional[ast.Dict] = None
                if isinstance(arg, ast.Dict):
                    d = arg
                elif isinstance(arg, ast.Name):
                    d = dicts_by_name.get((scope, arg.id)) or \
                        dicts_by_name.get((None, arg.id))
                if d is None:
                    continue
                for k, v in zip(d.keys, d.values):
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        yield mod, k.value, v, k.lineno, fn


def _has_inflight(fn: FunctionInfo) -> bool:
    for call, _t in fn.calls:
        name = call_name(call.func)
        if name and name.rsplit(".", 1)[-1] == "inflight":
            return True
    return False


def _blocking_evidence(graph: CallGraph, root: str) -> Optional[Tuple[str, str, int]]:
    """First blocking call transitively reachable from ``root``:
    (label, rel path, line). Lock acquires don't count — they are R1's
    concern and are typically short."""
    chains = graph.reachable([root], max_depth=_MAX_DEPTH)
    for qual in sorted(chains, key=lambda q: len(chains[q])):
        fn = graph.functions[qual]
        for call, _t in fn.calls:
            label = classify_blocking(
                call, graph.resolved_external(fn, call))
            if label is None or label.startswith("lock acquire"):
                continue
            return label, fn.module.rel, call.lineno
    return None


def check(project: Project) -> List[Finding]:
    graph: CallGraph = project.graph
    long_set, long_site = _long_methods(project)
    findings: List[Finding] = []
    registered: Set[str] = set()
    saw_table = False

    for mod, method, hexpr, lineno, encl in _handler_tables(project, graph):
        saw_table = True
        registered.add(method)
        if isinstance(hexpr, ast.Lambda):
            # lambdas are trivial ping-style handlers; a blocking lambda
            # would be caught by the direct scan below
            blocking = _lambda_blocking(graph, mod, encl, hexpr)
            target = None
        else:
            dotted = call_name(hexpr)
            from raydp_tpu.analysis.rules_signals import _resolve_ref
            target = _resolve_ref(graph, mod, encl, dotted) if dotted else None
            blocking = _blocking_evidence(graph, target) if target else None
        if blocking is None:
            continue
        if method in long_set:
            continue
        if target and _has_inflight(graph.functions[target]):
            continue
        label, where, bline = blocking
        findings.append(Finding(
            rule=RULE, name="blocking-handler-not-long", severity="error",
            path=mod.rel, line=lineno, col=0,
            message=f"handler '{method}' does {label} (at {where}:{bline}) "
                    f"but is not in _LONG_HANDLER_METHODS and has no "
                    f"inflight() bracket; the watchdog will flag it as a "
                    f"stall and may escalate",
            scope=encl.qualname if encl else "",
        ))

    if saw_table and long_site is not None:
        mod, line = long_site
        for method in sorted(long_set - registered):
            findings.append(Finding(
                rule=RULE, name="stale-long-entry", severity="warning",
                path=mod.rel, line=line, col=0,
                message=f"_LONG_HANDLER_METHODS entry '{method}' is not "
                        f"registered by any scanned handler table",
                scope="",
            ))
    return findings


def _lambda_blocking(graph: CallGraph, mod: ModuleInfo,
                     encl: Optional[FunctionInfo],
                     lam: ast.Lambda) -> Optional[Tuple[str, str, int]]:
    fn = graph.function_at(mod, lam)
    for node in walk_no_nested(lam.body):
        if isinstance(node, ast.Call):
            resolved = graph.resolved_external(fn, node) if fn else ""
            label = classify_blocking(node, resolved)
            if label and not label.startswith("lock acquire"):
                return label, mod.rel, node.lineno
    return None
