"""Engine core: file loading, rule registry, suppressions, results.

The engine parses every file once into a :class:`ModuleInfo`, builds a
project-wide :class:`~raydp_tpu.analysis.callgraph.CallGraph`, runs
each enabled rule's ``check(project)``, then filters the findings
through inline suppressions and the baseline. Rules are pure functions
over the parsed project — no imports of the analyzed code ever happen,
so the checker can run against broken or heavyweight modules.
"""
from __future__ import annotations

import ast
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "ModuleInfo",
    "Project",
    "AnalysisResult",
    "RULES",
    "run_analysis",
]

# Suppression comment: ``# raydp: ignore[R1]`` / ``ignore[lock-order]``
# / ``ignore[all]``; several tokens comma-separated. Valid on the
# finding's own line or the line directly above it.
_SUPPRESS_RE = re.compile(r"#\s*raydp:\s*ignore\[([A-Za-z0-9_,\- ]+)\]")

SEVERITIES = ("error", "warning", "info")


@dataclass
class Finding:
    """One diagnostic. ``rule`` is the family id (``R1``…``R5``),
    ``name`` the specific check (``lock-held-blocking``), ``scope``
    the enclosing function/class qualname (stable across line drift —
    it anchors the baseline fingerprint)."""

    rule: str
    name: str
    severity: str
    path: str  # repo-relative
    line: int
    col: int
    message: str
    scope: str = ""
    fingerprint: str = ""  # filled by the engine (needs dup indices)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule}[{self.name}] {self.severity}: {self.message}"
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "name": self.name,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "scope": self.scope,
            "fingerprint": self.fingerprint,
        }


@dataclass
class ModuleInfo:
    """One parsed source file."""

    path: str  # absolute
    rel: str  # repo-relative, '/'-separated
    name: str  # dotted module name relative to the repo root
    tree: ast.Module
    lines: List[str]
    suppressions: Dict[int, set] = field(default_factory=dict)

    def source_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclass
class Project:
    """Everything the rules see: parsed modules, the shared call graph,
    and the documentation corpus for the parity checks."""

    root: str
    modules: Dict[str, ModuleInfo]  # keyed by rel path
    by_name: Dict[str, ModuleInfo]  # keyed by dotted module name
    docs: Dict[str, str]  # rel path -> raw text of doc files
    graph: Any = None  # CallGraph, attached after construction

    def module_endswith(self, suffix: str) -> Optional[ModuleInfo]:
        for rel, mod in self.modules.items():
            if rel.endswith(suffix):
                return mod
        return None


@dataclass
class AnalysisResult:
    findings: List[Finding]  # active (not suppressed, not baselined)
    suppressed: int
    baselined: int
    stale_baseline: List[str]
    files: int
    seconds: float
    parse_errors: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "stale_baseline": self.stale_baseline,
            "files": self.files,
            "seconds": round(self.seconds, 3),
            "parse_errors": self.parse_errors,
        }


# -- file discovery -----------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", ".venv", "node_modules")
                ]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        out.append(os.path.join(dirpath, f))
    return sorted(set(out))


def _find_root(files: Sequence[str], explicit: Optional[str]) -> str:
    """Repo root: the parent of the top-most package directory (the
    directory holding the first scanned package, e.g. the parent of
    ``raydp_tpu/``). Falls back to the common prefix of the inputs."""
    if explicit:
        return os.path.abspath(explicit)
    candidates = []
    for f in files:
        d = os.path.dirname(f)
        # climb while the directory is a package (__init__.py present)
        while os.path.isfile(os.path.join(d, "__init__.py")):
            d = os.path.dirname(d)
        candidates.append(d)
    if not candidates:
        return os.getcwd()
    root = os.path.commonpath(candidates)
    return root


def _load_docs(root: str, docs_dir: Optional[str]) -> Dict[str, str]:
    texts: Dict[str, str] = {}
    doc_roots = []
    if docs_dir:
        doc_roots.append(os.path.abspath(docs_dir))
    else:
        doc_roots.append(os.path.join(root, "doc"))
        doc_roots.append(os.path.join(root, "docs"))
    for base in doc_roots:
        if not os.path.isdir(base):
            continue
        for dirpath, _dirnames, filenames in os.walk(base):
            for f in sorted(filenames):
                if f.endswith((".md", ".rst", ".txt")):
                    path = os.path.join(dirpath, f)
                    try:
                        with open(path, "r", encoding="utf-8") as fh:
                            texts[os.path.relpath(path, root)] = fh.read()
                    except OSError:
                        pass
    readme = os.path.join(root, "README.md")
    if os.path.isfile(readme):
        try:
            with open(readme, "r", encoding="utf-8") as fh:
                texts["README.md"] = fh.read()
        except OSError:
            pass
    return texts


def _parse_suppressions(lines: List[str]) -> Dict[int, set]:
    out: Dict[int, set] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            tokens = {t.strip() for t in m.group(1).split(",") if t.strip()}
            out[i] = tokens
    return out


def _module_name(rel: str) -> str:
    name = rel[:-3] if rel.endswith(".py") else rel
    parts = name.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# -- rule registry ------------------------------------------------------
# Populated lazily so core.py has no import cycle with the rule modules.


def _rule_modules():
    from raydp_tpu.analysis import (
        rules_jax,
        rules_locks,
        rules_rpc,
        rules_signals,
        rules_simclock,
        rules_telemetry,
    )

    return {
        "R1": rules_locks,
        "R2": rules_signals,
        "R3": rules_rpc,
        "R4": rules_telemetry,
        "R5": rules_jax,
        "R6": rules_simclock,
    }


RULES = {
    "R1": "lock-discipline: inversions + locks held across blocking calls",
    "R2": "signal-safety: no locks/logging/allocation in handler paths",
    "R3": "rpc-handler discipline: blocking handlers must be long-stall "
          "registered or inflight()-bracketed",
    "R4": "telemetry consistency: metric/family/env-var doc parity",
    "R5": "jax hazards: host syncs in jit/step loops, missing donation",
    "R6": "clock-seam discipline: no direct time.monotonic/sleep in "
          "simulable modules (control/, serve/batching.py, sim/)",
}


def _is_suppressed(f: Finding, mod: Optional[ModuleInfo]) -> bool:
    """A suppression applies on the finding's own line or anywhere in
    the contiguous comment block directly above it."""
    if mod is None:
        return False
    lines = [f.line]
    above = f.line - 1
    while above >= 1 and mod.source_at(above).lstrip().startswith("#"):
        lines.append(above)
        above -= 1
    for line in lines:
        tokens = mod.suppressions.get(line)
        if not tokens:
            continue
        if "all" in tokens or f.rule in tokens or f.name in tokens:
            return True
    return False


def _fingerprint_all(findings: List[Finding]) -> None:
    """Stable ids: rule|path|scope|name|slug(message)|dup-index. Line
    numbers are deliberately excluded so unrelated edits above a
    baselined finding don't un-baseline it."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda x: (x.path, x.line, x.col)):
        slug = re.sub(r"[0-9]+", "#", f.message)[:120]
        base = f"{f.rule}|{f.path}|{f.scope}|{f.name}|{slug}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        f.fingerprint = base if n == 0 else f"{base}|{n}"


def run_analysis(
    paths: Sequence[str],
    rules: Optional[Iterable[str]] = None,
    root: Optional[str] = None,
    docs_dir: Optional[str] = None,
    baseline: Optional[Dict[str, Any]] = None,
) -> AnalysisResult:
    """Analyze ``paths`` and return the filtered result.

    ``baseline`` is the loaded baseline document (see
    :mod:`~raydp_tpu.analysis.baseline`); findings whose fingerprint it
    contains are counted but not reported as active.
    """
    t0 = time.perf_counter()
    files = _iter_py_files(paths)
    repo_root = _find_root(files, root)

    modules: Dict[str, ModuleInfo] = {}
    by_name: Dict[str, ModuleInfo] = {}
    findings: List[Finding] = []
    parse_errors = 0
    for path in files:
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as exc:
            parse_errors += 1
            findings.append(Finding(
                rule="R0", name="parse-error", severity="error",
                path=rel, line=getattr(exc, "lineno", 1) or 1, col=0,
                message=f"file could not be parsed: {exc}",
            ))
            continue
        lines = source.splitlines()
        mod = ModuleInfo(
            path=path, rel=rel, name=_module_name(rel), tree=tree,
            lines=lines, suppressions=_parse_suppressions(lines),
        )
        modules[rel] = mod
        by_name[mod.name] = mod

    project = Project(
        root=repo_root, modules=modules, by_name=by_name,
        docs=_load_docs(repo_root, docs_dir),
    )
    from raydp_tpu.analysis.callgraph import CallGraph

    project.graph = CallGraph(project)

    enabled = set(rules) if rules else set(RULES)
    for rule_id, rule_mod in _rule_modules().items():
        if rule_id not in enabled:
            continue
        try:
            findings.extend(rule_mod.check(project))
        except Exception as exc:  # a broken rule must not hide the rest
            findings.append(Finding(
                rule=rule_id, name="rule-crashed", severity="error",
                path="<engine>", line=1, col=0,
                message=f"rule {rule_id} crashed: "
                        f"{type(exc).__name__}: {exc}",
            ))

    # inline suppressions
    active: List[Finding] = []
    suppressed = 0
    for f in findings:
        if _is_suppressed(f, modules.get(f.path)):
            suppressed += 1
        else:
            active.append(f)

    _fingerprint_all(active)

    # baseline ratchet
    baselined = 0
    stale: List[str] = []
    if baseline:
        known = set((baseline.get("findings") or {}).keys())
        matched = set()
        remaining = []
        for f in active:
            if f.fingerprint in known:
                baselined += 1
                matched.add(f.fingerprint)
            else:
                remaining.append(f)
        active = remaining
        stale = sorted(known - matched)

    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.name))
    return AnalysisResult(
        findings=active, suppressed=suppressed, baselined=baselined,
        stale_baseline=stale, files=len(files),
        seconds=time.perf_counter() - t0, parse_errors=parse_errors,
    )
