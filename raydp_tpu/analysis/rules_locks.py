"""R1 lock-discipline.

Builds a per-module lock-acquisition graph and reports:

* ``lock-held-blocking`` (error) — a blocking call (RPC, queue get,
  ``time.sleep``, subprocess, ``future.result()``, ``Event.wait``,
  thread ``join``) executed while a lock is held. This is the shape of
  the ``SPMDJob`` dispatch stalls and the PR 3 flight-recorder hang.
* ``lock-held-blocking-transitive`` (warning) — same, but the blocking
  call sits one resolved call away (depth 1 only, to stay quiet).
* ``lock-order-inversion`` (error) — two locks acquired in both
  ``A→B`` and ``B→A`` order somewhere in the same module.
* ``lock-reacquire`` (error) — a non-reentrant lock acquired while
  already held (guaranteed self-deadlock).

Lock identity is normalized so ``self._mu`` inside ``class C`` of
module ``m`` becomes ``m.C._mu`` — order edges line up across methods.
The walk is path-insensitive inside a function (branch-local
``acquire()`` effects don't leak out) but tracks ``try/finally``
release so the canonical ``acquire(); try: ...; finally: release()``
idiom doesn't poison the rest of the function.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raydp_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    call_name,
    classify_blocking,
    qual_last,
    walk_no_nested,
)
from raydp_tpu.analysis.core import Finding, ModuleInfo, Project

RULE = "R1"

_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
}
_REENTRANT_CTORS = {"RLock", "threading.RLock", "multiprocessing.RLock"}

# name-based fallback when the constructor site isn't visible
_LOCKY_NAMES = ("lock", "_mu", "mutex", "_cv", "cond")


def _looks_like_lock(dotted: str) -> bool:
    last = qual_last(dotted).lower()
    return any(last == t or last.endswith(t) for t in _LOCKY_NAMES)


class _LockRegistry:
    """Which attributes/names are locks, and which are reentrant."""

    def __init__(self) -> None:
        self.kinds: Dict[str, str] = {}  # normalized id -> ctor name

    def record(self, norm: str, ctor: str) -> None:
        self.kinds[norm] = ctor

    def is_known(self, norm: str) -> bool:
        return norm in self.kinds

    def is_reentrant(self, norm: str) -> bool:
        return self.kinds.get(norm) in _REENTRANT_CTORS


def _normalize(dotted: str, fn: Optional[FunctionInfo], mod: ModuleInfo) -> str:
    """``self._mu`` in ``m.C.f`` → ``m.C._mu``; bare ``x`` → ``m.x``."""
    if dotted.startswith("self.") and fn is not None and fn.cls:
        return f"{fn.cls}.{dotted[len('self.'):]}"
    if "." not in dotted:
        return f"{mod.name}.{dotted}"
    return dotted


def _collect_locks(project: Project, graph: CallGraph) -> _LockRegistry:
    reg = _LockRegistry()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            ctor = call_name(node.value.func)
            if ctor not in _LOCK_CTORS:
                continue
            fn = graph.enclosing_function(mod, node.lineno)
            for t in node.targets:
                tgt = call_name(t)
                if tgt:
                    reg.record(_normalize(tgt, fn, mod), ctor)
    return reg


def _lock_expr(expr: ast.AST, reg: _LockRegistry,
               fn: Optional[FunctionInfo], mod: ModuleInfo) -> Optional[str]:
    """Normalized lock id if ``expr`` denotes a lock, else None."""
    dotted = call_name(expr)
    if not dotted:
        return None
    norm = _normalize(dotted, fn, mod)
    if reg.is_known(norm) or _looks_like_lock(dotted):
        return norm
    return None


def check(project: Project) -> List[Finding]:
    graph: CallGraph = project.graph
    reg = _collect_locks(project, graph)
    findings: List[Finding] = []
    # module -> ordered (outer, inner, path, line) edges for inversions
    edges: Dict[str, List[Tuple[str, str, str, int]]] = {}

    for fn in graph.functions.values():
        if isinstance(fn.node, ast.Lambda):
            continue
        scanner = _Scanner(fn, graph, reg, findings,
                           edges.setdefault(fn.module.name, []))
        scanner.scan(fn.node.body, [])

    for es in edges.values():
        _report_inversions(es, findings)
    return findings


class _Scanner:
    """Recursive statement walker tracking the held-lock stack."""

    def __init__(self, fn: FunctionInfo, graph: CallGraph,
                 reg: _LockRegistry, findings: List[Finding],
                 edges: List[Tuple[str, str, str, int]]):
        self.fn = fn
        self.mod = fn.module
        self.graph = graph
        self.reg = reg
        self.findings = findings
        self.edges = edges

    def scan(self, stmts, held: List[str]) -> None:
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in stmt.items:
                    lock = _lock_expr(item.context_expr, self.reg,
                                      self.fn, self.mod)
                    if lock is not None:
                        self._on_acquire(lock, stmt, held + acquired)
                        acquired.append(lock)
                    else:
                        self._scan_expr(item.context_expr, held)
                self.scan(stmt.body, held + acquired)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test, held)
                self.scan(stmt.body, held)
                self.scan(stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, held)
                self.scan(stmt.body, held)
                self.scan(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                self.scan(stmt.body, held)
                for h in stmt.handlers:
                    self.scan(h.body, held)
                self.scan(stmt.orelse, held)
                self.scan(stmt.finalbody, held)
                # `acquire(); try: ... finally: release()` — honour the
                # finally-release so code after the try isn't poisoned
                for lock in self._released_in(stmt.finalbody):
                    if lock in held:
                        held.remove(lock)
            else:
                self._scan_simple(stmt, held)

    # -- helpers -------------------------------------------------------

    def _released_in(self, stmts) -> List[str]:
        out = []
        for stmt in stmts:
            for node in walk_no_nested(stmt):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "release":
                    lock = _lock_expr(node.func.value, self.reg,
                                      self.fn, self.mod)
                    if lock is not None:
                        out.append(lock)
        return out

    def _scan_expr(self, expr: ast.AST, held: List[str]) -> None:
        if expr is None:
            return
        for node in walk_no_nested(expr):
            if isinstance(node, ast.Call):
                self._check_call(node, held, mutate=None)

    def _scan_simple(self, stmt: ast.stmt, held: List[str]) -> None:
        for node in walk_no_nested(stmt):
            if isinstance(node, ast.Call):
                self._check_call(node, held, mutate=held)

    def _check_call(self, node: ast.Call, held: List[str],
                    mutate: Optional[List[str]]) -> None:
        label = classify_blocking(
            node, self.graph.resolved_external(self.fn, node))
        if label is None:
            if held:
                self._check_transitive(node, held)
            return
        if label.startswith("lock acquire"):
            lock = _lock_expr(node.func.value, self.reg, self.fn, self.mod) \
                if isinstance(node.func, ast.Attribute) else None
            if lock is not None:
                self._on_acquire(lock, node, held)
                if mutate is not None and lock not in mutate:
                    mutate.append(lock)
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "release":
            lock = _lock_expr(node.func.value, self.reg, self.fn, self.mod)
            if lock is not None and mutate is not None and lock in mutate:
                mutate.remove(lock)
            return
        if not held:
            return
        # Condition.wait() releases the lock it is paired with
        if ".wait()" in label and isinstance(node.func, ast.Attribute):
            cv = _lock_expr(node.func.value, self.reg, self.fn, self.mod)
            if cv is not None and cv in held:
                return
        self.findings.append(Finding(
            rule=RULE, name="lock-held-blocking", severity="error",
            path=self.mod.rel, line=node.lineno, col=node.col_offset,
            message=f"{label} while holding {_short(held[-1])}; release "
                    f"the lock or move the blocking work outside it",
            scope=self.fn.qualname,
        ))

    def _on_acquire(self, lock: str, node: ast.AST,
                    held: List[str]) -> None:
        if not held:
            return
        for outer in held:
            self.edges.append((outer, lock, self.mod.rel, node.lineno))
        if lock in held and not self.reg.is_reentrant(lock):
            self.findings.append(Finding(
                rule=RULE, name="lock-reacquire", severity="error",
                path=self.mod.rel, line=node.lineno, col=node.col_offset,
                message=f"{_short(lock)} re-acquired while already held "
                        f"(non-reentrant: self-deadlock)",
                scope=self.fn.qualname,
            ))

    def _check_transitive(self, node: ast.Call, held: List[str]) -> None:
        target = self.graph.resolve_call(self.fn, node)
        if not target:
            return
        callee = self.graph.functions.get(target)
        if callee is None:
            return
        for call, _t in callee.calls:
            label = classify_blocking(
                call, self.graph.resolved_external(callee, call))
            if label is None or label.startswith("lock acquire"):
                continue
            self.findings.append(Finding(
                rule=RULE, name="lock-held-blocking-transitive",
                severity="warning",
                path=self.mod.rel, line=node.lineno, col=node.col_offset,
                message=f"call to {qual_last(target)}() while holding "
                        f"{_short(held[-1])}; callee does {label} "
                        f"(at {callee.module.rel}:{call.lineno})",
                scope=self.fn.qualname,
            ))
            return  # one report per call site is enough


def _short(lock: str) -> str:
    parts = lock.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else lock


def _report_inversions(edges: List[Tuple[str, str, str, int]],
                       findings: List[Finding]) -> None:
    order: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for outer, inner, path, line in edges:
        if outer == inner:
            continue
        order.setdefault((outer, inner), (path, line))
    reported: Set[frozenset] = set()
    for (a, b), (path, line) in sorted(order.items()):
        pair = frozenset((a, b))
        if pair in reported or (b, a) not in order:
            continue
        reported.add(pair)
        other_path, other_line = order[(b, a)]
        findings.append(Finding(
            rule=RULE, name="lock-order-inversion", severity="error",
            path=path, line=line, col=0,
            message=f"lock order inversion: {_short(a)} -> {_short(b)} "
                    f"here but {_short(b)} -> {_short(a)} at "
                    f"{other_path}:{other_line}; pick one global order",
            scope="",
        ))
