"""R6 — clock-seam discipline: simulable modules never read the wall
clock directly.

The simulator (:mod:`raydp_tpu.sim`) works by installing a virtual
clock behind :mod:`raydp_tpu.utils.clock`. That only holds if every
time read, sleep, and timed wait in the simulated code routes through
the seam: one stray ``time.monotonic()`` in the arbiter and a
virtual-hour cooldown silently compares a virtual timestamp against a
wall timestamp — the worst kind of bug, because nothing crashes and
every simulated cooldown/TTL/linger number is quietly wrong.

The rule bans direct ``time.monotonic`` / ``time.time`` /
``time.sleep`` / ``time.perf_counter`` calls (and ``threading.Timer``
construction, which embeds a real-clock sleep) in the modules the
simulator runs:

* everything under ``raydp_tpu/control/``
* ``raydp_tpu/serve/batching.py`` (the queue the sim drives)
* everything under ``raydp_tpu/sim/`` (the simulator itself must go
  through the seam's ``Clock`` objects, not the wall)

``time.time()`` for *wall-stamping* records (not durations) is out of
scope elsewhere in the tree; inside the fence it is still flagged —
the simulated timeline must be internally consistent.

Fix: ``from raydp_tpu.utils import clock as _clock`` and use
``_clock.monotonic() / sleep / wait_on / wait_event / call_later /
defer``. A deliberate wall read (e.g. a real-time watchdog inside the
sim) instantiates ``clock.Clock()`` explicitly — the real
implementation, reached through the seam's type, which the rule
accepts.
"""
from __future__ import annotations

import ast
from typing import List

from raydp_tpu.analysis.core import Finding, Project

RULE = "R6"

#: Module prefixes (repo-relative, '/'-separated) inside the fence.
FENCED_PREFIXES = ("raydp_tpu/control/", "raydp_tpu/sim/")
#: Individual fenced files.
FENCED_FILES = ("raydp_tpu/serve/batching.py",)

_BANNED_TIME_ATTRS = (
    "monotonic", "time", "sleep", "perf_counter", "monotonic_ns",
    "perf_counter_ns",
)


def _fenced(rel: str) -> bool:
    return rel in FENCED_FILES or any(
        rel.startswith(p) for p in FENCED_PREFIXES
    )


def _scope_of(stack: List[str]) -> str:
    return ".".join(stack)


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str, findings: List[Finding]):
        self.rel = rel
        self.findings = findings
        self.stack: List[str] = []
        # Names that alias the time module in this file
        # (``import time``, ``import time as t``).
        self.time_aliases = {"time"}

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "time":
                self.time_aliases.add(alias.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                if alias.name in _BANNED_TIME_ATTRS:
                    self._flag(
                        node,
                        f"from time import {alias.name}",
                        f"imports time.{alias.name} directly; route "
                        "through raydp_tpu.utils.clock so the "
                        "simulator's virtual clock applies",
                    )
        self.generic_visit(node)

    def _walk_scope(self, node, name: str) -> None:
        self.stack.append(name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._walk_scope(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._walk_scope(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._walk_scope(node, node.name)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if (isinstance(base, ast.Name)
                    and base.id in self.time_aliases
                    and fn.attr in _BANNED_TIME_ATTRS):
                self._flag(
                    node,
                    f"{base.id}.{fn.attr}()",
                    f"calls time.{fn.attr}() directly; use "
                    "raydp_tpu.utils.clock so simulations replace the "
                    "clock (doc/simulation.md)",
                )
            elif (isinstance(base, ast.Name)
                    and base.id == "threading"
                    and fn.attr == "Timer"):
                self._flag(
                    node,
                    "threading.Timer(...)",
                    "constructs threading.Timer directly (a real-clock "
                    "sleep); use raydp_tpu.utils.clock.call_later",
                )
        self.generic_visit(node)

    def _flag(self, node: ast.AST, what: str, why: str) -> None:
        self.findings.append(Finding(
            rule=RULE,
            name="direct-wall-clock",
            severity="error",
            path=self.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=f"{what} inside the clock-seam fence: {why}",
            scope=_scope_of(self.stack),
        ))


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for rel, mod in sorted(project.modules.items()):
        if not _fenced(rel):
            continue
        visitor = _Visitor(rel, findings)
        visitor.visit(mod.tree)
    return findings
