"""Sharded ML dataset: the DataFrame → trainer handoff.

Capability parity with the reference's RayMLDataset layer
(reference: python/raydp/spark/dataset.py:43-457 — RecordPiece shards,
``from_spark``/``from_parquet``/``to_torch``, equal-sample division via
``divide_blocks``, locality-aware shard selection). TPU-first differences:
shards map to the **data axis of the device mesh** (one shard per dp rank),
and consumption is a double-buffered ``jax.device_put`` infeed instead of a
torch DataLoader (though ``to_torch`` exists for interop).
"""
from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa

from raydp_tpu.dataframe.scheduler import (
    PendingPartition,
    is_pending,
    resolve_one,
)
from raydp_tpu.store.object_store import ObjectRef, ObjectStore
from raydp_tpu.utils.sharding import (
    BlockSlice,
    divide_blocks,
    divide_blocks_local,
    locality_fraction,
)

Block = Union[pa.Table, ObjectRef]


class MLDataset:
    """An immutable list of Arrow blocks + a shard plan over them.

    Every shard yields exactly ``ceil(total_rows / num_shards)`` samples per
    epoch (block reuse pads short shards) so SPMD data-parallel steps stay
    in lockstep.
    """

    def __init__(
        self,
        blocks: List[Block],
        num_shards: int,
        shuffle: bool = False,
        shuffle_seed: Optional[int] = None,
        store: Optional[ObjectStore] = None,
        rank_nodes: Optional[List[str]] = None,
    ):
        if not blocks:
            raise ValueError("MLDataset needs at least one block")
        self._blocks = list(blocks)
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.shuffle_seed = shuffle_seed
        self._store = store
        self.rank_nodes = list(rank_nodes) if rank_nodes is not None else None
        if len(blocks) < num_shards:
            raise ValueError(
                f"{len(blocks)} blocks cannot feed {num_shards} shards; "
                "repartition the DataFrame first"
            )
        # Streaming handoff: blocks may still be in-flight ETL tasks
        # (PendingPartition). The shard plan needs every block's size, so
        # it is DEFERRED until a consumer actually needs it
        # (_ensure_plan) — the epoch-0 prefix streamer reads only the
        # monotone lower bound in ``_known`` and never barriers.
        self._plan_mu = threading.Lock()
        self._known: List[Optional[int]] = []
        for b in self._blocks:
            if is_pending(b):
                self._known.append(None)
            elif isinstance(b, ObjectRef):
                self._known.append(
                    b.num_rows if b.num_rows >= 0 else None
                )
            else:
                self._known.append(b.num_rows)
        self._block_sizes: Optional[List[int]] = None
        self.block_nodes: Optional[List[Optional[str]]] = None
        self._shard_plan: Optional[Dict[int, List[BlockSlice]]] = None
        for i, b in enumerate(self._blocks):
            if is_pending(b):
                b.future.add_done_callback(
                    lambda f, i=i: self._note_block(i, f)
                )
        if not any(is_pending(b) for b in self._blocks):
            self._ensure_plan()

    @property
    def blocks(self) -> List[Block]:
        """Concrete blocks (ObjectRefs / tables) — the materialized view
        every non-streaming consumer (store feed, SPMD fit, shard
        readers) sees, so it BARRIERS on blocks still in flight.
        Streaming consumers read ``known_rows()`` /
        ``iter_prefix_tables()`` instead and never touch this."""
        if any(is_pending(b) for b in self._blocks):
            resolved = [resolve_one(b) for b in self._blocks]
            with self._plan_mu:
                self._blocks = resolved
        return self._blocks

    def has_pending_blocks(self) -> bool:
        """True while any block is still an in-flight ETL partition."""
        return any(
            is_pending(b) and not b.future.done() for b in self._blocks
        )

    def known_rows(self) -> Tuple[int, bool]:
        """(sum of block sizes known SO FAR, whether all are known).
        The sum only grows as pending blocks land, so it is a safe lower
        bound of ``total_rows`` — what the epoch-0 prefix streamer sizes
        its emit limit with."""
        with self._plan_mu:
            vals = list(self._known)
        return (
            sum(v for v in vals if v is not None),
            all(v is not None for v in vals),
        )

    def iter_prefix_tables(self) -> Iterator[Tuple[int, pa.Table]]:
        """Yield ``(block_index, table)`` in block order, waiting on each
        pending block IN ORDER — the dataset prefix streams out while
        later blocks are still being produced."""
        for i, b in enumerate(list(self._blocks)):
            table = self._resolve(resolve_one(b))
            with self._plan_mu:
                if self._known[i] is None:
                    self._known[i] = table.num_rows
            yield i, table

    def _note_block(self, i: int, fut) -> None:
        """Done-callback of pending block ``i``: record its row count the
        moment it lands (feeds ``known_rows``)."""
        if fut.exception() is not None:
            return
        ref = fut.result()
        rows = getattr(ref, "num_rows", -1)
        if rows is None or rows < 0:
            return  # unknowable without a fetch; prefix iteration fills it
        with self._plan_mu:
            if self._known[i] is None:
                self._known[i] = int(rows)

    def _ensure_plan(self) -> None:
        """Barrier: resolve every block and build the shard plan. All
        shard accessors funnel through here; until one does, a dataset
        over pending blocks never blocks its creator."""
        if self._shard_plan is not None:
            return
        # Resolve OUTSIDE the lock (arbitrarily long); idempotent, so a
        # racing second consumer just re-resolves the same futures.
        blocks = [resolve_one(b) for b in self._blocks]
        sizes = [self._block_rows(b) for b in blocks]
        with self._plan_mu:
            if self._shard_plan is not None:
                return
            self._blocks = blocks
            self._block_sizes = sizes
            self._known = [int(s) for s in sizes]
            # Locality-aware division when the consumer topology is
            # known: rank_nodes[r] names the node rank r runs on; ref
            # blocks carry their node, so shard plans keep bytes
            # node-local (reference: locality-preferring shard
            # selection, dataset.py:411-443).
            self.block_nodes = [
                b.node_id if isinstance(b, ObjectRef) else None
                for b in blocks
            ]
            if self.rank_nodes is not None and any(
                n is not None for n in self.block_nodes
            ):
                nodes = [n or "node-0" for n in self.block_nodes]
                self._shard_plan = divide_blocks_local(
                    sizes, self.num_shards, nodes, self.rank_nodes,
                    self.shuffle, self.shuffle_seed,
                )
            else:
                self._shard_plan = divide_blocks(
                    sizes, self.num_shards, self.shuffle, self.shuffle_seed
                )

    def locality(self) -> Optional[float]:
        """Fraction of planned samples that are node-local (None when no
        topology was supplied)."""
        if self.rank_nodes is None:
            return None
        self._ensure_plan()
        nodes = [n or "node-0" for n in self.block_nodes]
        return locality_fraction(self._shard_plan, nodes, self.rank_nodes)

    # -- constructors ---------------------------------------------------
    @staticmethod
    def from_df(
        df,
        num_shards: int,
        shuffle: bool = False,
        shuffle_seed: Optional[int] = None,
        owner_transfer: bool = True,
        rank_nodes: Optional[List[str]] = None,
    ) -> "MLDataset":
        """From a raydp_tpu DataFrame (reference: RayMLDataset.from_spark,
        dataset.py:283-310). Repartitions up to ``num_shards`` if short.

        ``rank_nodes`` (one node id per shard rank) turns on
        locality-preferring shard assignment."""
        if df.num_partitions < num_shards:
            df = df.repartition(num_shards)
        from raydp_tpu.context import current_session

        session = current_session()
        if session is not None:
            # Streaming handoff: partitions still being produced arrive
            # as pending futures (owner transfer chained onto each), so
            # to_jax() can ingest early blocks while late ETL partitions
            # are in flight.
            refs = df._to_block_parts(owner_transfer=owner_transfer)
            if refs is None:
                refs = df.to_object_refs(owner_transfer=owner_transfer)
            # The resolver (not the raw store) so blocks written on any
            # node of a multi-host cluster resolve from the driver.
            store = session.cluster.resolver
            return MLDataset(
                refs, num_shards, shuffle, shuffle_seed, store,
                rank_nodes=rank_nodes,
            )
        return MLDataset(
            df.collect_partitions(), num_shards, shuffle, shuffle_seed,
            rank_nodes=rank_nodes,
        )

    @staticmethod
    def from_refs(
        refs: Sequence[ObjectRef],
        num_shards: int,
        shuffle: bool = False,
        shuffle_seed: Optional[int] = None,
        rank_nodes: Optional[List[str]] = None,
    ) -> "MLDataset":
        """Directly from ObjectRefs (parity with the reference's
        ``ray.data.from_arrow_refs`` entry, dataset.py:470-480). Resolves
        through the live session's node-aware resolver."""
        from raydp_tpu.context import require_session

        session = require_session()
        return MLDataset(
            list(refs), num_shards, shuffle, shuffle_seed,
            store=session.cluster.resolver, rank_nodes=rank_nodes,
        )

    @staticmethod
    def from_parquet(
        paths: Union[str, Sequence[str]],
        num_shards: int,
        shuffle: bool = False,
        shuffle_seed: Optional[int] = None,
        columns: Optional[List[str]] = None,
    ) -> "MLDataset":
        """Directly from parquet row groups (reference:
        RayMLDataset.from_parquet, dataset.py:313-349)."""
        import pyarrow.parquet as pq

        from raydp_tpu.dataframe.io import _expand

        if isinstance(paths, str):
            files = _expand(paths, (".parquet", ".pq"))
        else:
            files = list(paths)
        tables: List[pa.Table] = []
        for f in files:
            pf = pq.ParquetFile(f)
            for rg in range(pf.num_row_groups):
                tables.append(pf.read_row_group(rg, columns=columns))
        return MLDataset(tables, num_shards, shuffle, shuffle_seed)

    def to_df(self):
        """Back to a DataFrame — the reverse data path (C8 parity with
        ``ray_dataset_to_spark_dataframe``, reference:
        python/raydp/spark/dataset.py:506-577). Ref blocks become the
        frame's partitions with zero copies; in-memory blocks re-enter via
        the executor's scatter path."""
        import raydp_tpu.dataframe as rdf
        from raydp_tpu.context import current_session

        self._ensure_plan()
        if all(isinstance(b, ObjectRef) for b in self.blocks):
            session = current_session()
            if session is not None:
                return rdf.from_refs(self.blocks)
        tables = [self._resolve(b) for b in self.blocks]
        from raydp_tpu.dataframe.io import _distribute

        return _distribute(tables)

    # -- introspection --------------------------------------------------
    @property
    def shard_plan(self) -> Dict[int, List[BlockSlice]]:
        """rank → block slices. Building it needs every block's size, so
        the first read barriers on in-flight blocks."""
        self._ensure_plan()
        return self._shard_plan

    @property
    def block_sizes(self) -> List[int]:
        """Per-block row counts (barriers on in-flight blocks)."""
        self._ensure_plan()
        return list(self._block_sizes)

    @property
    def total_rows(self) -> int:
        self._ensure_plan()
        return sum(self._block_sizes)

    @property
    def rows_per_shard(self) -> int:
        return math.ceil(self.total_rows / self.num_shards)

    def schema(self) -> pa.Schema:
        # Only block 0 need exist — never barriers on the whole plan.
        return self._resolve(resolve_one(self._blocks[0])).schema

    # -- shard access ---------------------------------------------------
    def shard_tables(self, rank: int) -> List[pa.Table]:
        """The (sliced) blocks assigned to ``rank``."""
        self._ensure_plan()
        if rank not in self._shard_plan:
            raise IndexError(f"rank {rank} out of {self.num_shards}")
        out = []
        for s in self._shard_plan[rank]:
            table = self._resolve(self._blocks[s.block_index])
            if s.offset == 0 and s.num_samples == table.num_rows:
                out.append(table)
            else:
                out.append(table.slice(s.offset, s.num_samples))
        return out

    def shard_global_indices(self, rank: int) -> np.ndarray:
        """Global dataset row index (block order, then row order within
        block) of every sample in ``rank``'s plan, in plan order — the
        inverse of the shard plan. Inference uses this to scatter
        per-shard outputs back to dataset order: padding rows map to the
        same global index as the row they duplicate, so a scatter
        overwrites them with identical values and the padded sample count
        collapses back to ``total_rows``. (Training never needs this —
        the equal-samples padding is a lockstep invariant of the
        reference's divide_blocks, python/raydp/utils.py:149-222, that
        must NOT leak into inference results.)"""
        self._ensure_plan()
        if rank not in self._shard_plan:
            raise IndexError(f"rank {rank} out of {self.num_shards}")
        starts = np.zeros(len(self._block_sizes), dtype=np.int64)
        if len(self._block_sizes) > 1:
            starts[1:] = np.cumsum(self._block_sizes[:-1])
        parts = [
            starts[s.block_index] + s.offset
            + np.arange(s.num_samples, dtype=np.int64)
            for s in self._shard_plan[rank]
        ]
        if not parts:
            return np.empty((0,), dtype=np.int64)
        return np.concatenate(parts)

    def shard_columns(
        self, rank: int, columns: Optional[List[str]] = None
    ) -> Dict[str, np.ndarray]:
        """Shard materialized as contiguous numpy columns (loader input)."""
        tables = self.shard_tables(rank)
        merged = (
            pa.concat_tables(tables, promote_options="default")
            if len(tables) > 1
            else tables[0]
        )
        names = columns or merged.column_names
        out: Dict[str, np.ndarray] = {}
        for name in names:
            # Direct Arrow→numpy (zero-copy when no nulls + numeric); no
            # pandas Series intermediary on the ingest path.
            out[name] = merged.column(name).to_numpy(zero_copy_only=False)
        return out

    def to_jax(
        self,
        feature_columns: List[str],
        label_column: Optional[str] = None,
        batch_size: int = 256,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        feature_dtype=np.float32,
        label_dtype=np.float32,
        prefetch: int = 2,
        device=None,
        drop_last: bool = False,
        transfer_coalesce: Optional[int] = None,
        transfer_window: int = 2,
    ):
        """Device-feeding batch iterator for this shard (the TPU-native
        counterpart of ``to_torch``, reference dataset.py:411-443).

        ``transfer_coalesce`` batches ship per ``device_put``; features
        and labels pack into ONE staged buffer per chunk, so a chunk is
        exactly one transfer. ``None`` = auto-size: on the device path,
        chunks grow toward ~128MB (``RAYDP_TRANSFER_CHUNK_MB``, capped at
        32 batches); on the host path (``device=None``) auto stays at one
        batch per chunk — there is no transfer to amortize and per-batch
        granularity keeps prefetch memory small. An EXPLICIT value is
        honored on both paths (host callers may want bigger gather chunks
        for cache efficiency); ``1`` = per-batch transfers. Up to
        ``transfer_window`` chunk transfers stay in flight — see
        loader.py's module docstring for why this matters on
        high-latency device links."""
        from raydp_tpu.data.loader import JaxShardLoader

        return JaxShardLoader(
            self,
            rank=rank,
            feature_columns=feature_columns,
            label_column=label_column,
            batch_size=batch_size,
            shuffle=shuffle,
            seed=seed,
            feature_dtype=feature_dtype,
            label_dtype=label_dtype,
            prefetch=prefetch,
            device=device,
            drop_last=drop_last,
            transfer_coalesce=transfer_coalesce,
            transfer_window=transfer_window,
        )

    def to_torch(
        self,
        feature_columns: List[str],
        label_column: str,
        batch_size: int = 256,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
    ):
        """Torch IterableDataset over this shard (API parity with the
        reference's TorchMLDataset, torch/torch_ml_dataset.py:25-111)."""
        from raydp_tpu.data.torch_adapter import TorchShardDataset

        return TorchShardDataset(
            self, rank, feature_columns, label_column, batch_size, shuffle,
            seed,
        )

    # -- internals ------------------------------------------------------
    def _resolve(self, block: Block) -> pa.Table:
        block = resolve_one(block)
        if isinstance(block, ObjectRef):
            store = self._store
            if store is not None:
                return store.get_arrow_table(block)
            from raydp_tpu.store.object_store import resolve_ambient_table

            return resolve_ambient_table(block)
        return block

    def _block_rows(self, block: Block) -> int:
        block = resolve_one(block)
        if isinstance(block, ObjectRef):
            if block.num_rows < 0:
                return self._resolve(block).num_rows
            return block.num_rows
        return block.num_rows
