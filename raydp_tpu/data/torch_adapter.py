"""Torch interop: iterate an MLDataset shard as a torch IterableDataset.

API parity with the reference's torch adapters
(reference: python/raydp/torch/torch_ml_dataset.py:25-111 —
TorchMLDataset/PrefetchedDataLoader). Torch here is CPU-only interop for
users migrating pipelines; the TPU path is ``MLDataset.to_jax``.
"""
from __future__ import annotations

from typing import List

import numpy as np


class TorchShardDataset:
    """torch.utils.data.IterableDataset over one shard (lazy torch import
    so the framework never requires torch)."""

    def __new__(cls, dataset, rank, feature_columns, label_column,
                batch_size, shuffle, seed):
        import torch
        from torch.utils.data import IterableDataset

        class _Impl(IterableDataset):
            def __init__(self):
                self._loader = dataset.to_jax(
                    feature_columns=feature_columns,
                    label_column=label_column,
                    batch_size=batch_size,
                    rank=rank,
                    shuffle=shuffle,
                    seed=seed,
                    prefetch=0,
                    device=None,
                )

            def __iter__(self):
                # Under DataLoader(num_workers>0) torch replicates the
                # IterableDataset per worker; split batches round-robin so
                # samples aren't duplicated (reference guards likewise via
                # get_worker_info, torch_ml_dataset.py:25-60).
                info = torch.utils.data.get_worker_info()
                wid = info.id if info is not None else 0
                nworkers = info.num_workers if info is not None else 1
                for i, (x, y) in enumerate(self._loader):
                    if i % nworkers != wid:
                        continue
                    yield (
                        torch.from_numpy(np.ascontiguousarray(x)),
                        torch.from_numpy(np.ascontiguousarray(y)),
                    )

            def __len__(self):
                return len(self._loader)

        return _Impl()
