from raydp_tpu.data.ml_dataset import MLDataset

__all__ = ["MLDataset"]
