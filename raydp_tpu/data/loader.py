"""Host→device batch pipeline: gather, stage, prefetch, device_put.

The hot path of training ingest. Per epoch:

  1. shard columns live as contiguous numpy arrays (zero-copy from Arrow
     where dtypes allow);
  2. a permutation is drawn (epoch-seeded — reshuffle every epoch like the
     reference's per-epoch shard shuffle, dataset.py:355-376);
  3. batches are assembled by the native row-gather kernel
     (raydp_tpu/native/src/gather.cpp) into reused staging buffers;
  4. a background thread keeps ``prefetch`` staged batches ahead;
  5. ``jax.device_put`` overlaps: batch N+1 is transferred while the
     caller computes on batch N (double buffering — keeps the TPU from
     stalling on HBM infeed).
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from raydp_tpu.native import lib as native
from raydp_tpu.utils.profiling import metrics


class JaxShardLoader:
    """Iterable over (features, labels) device arrays for one shard.

    Re-iterable: each ``iter()`` is a new epoch with a fresh permutation.
    """

    def __init__(
        self,
        dataset,
        rank: int,
        feature_columns: List[str],
        label_column: Optional[str],
        batch_size: int,
        shuffle: bool,
        seed: int,
        feature_dtype,
        label_dtype,
        prefetch: int,
        device,
        drop_last: bool,
    ):
        self._dataset = dataset
        self._rank = rank
        self.feature_columns = feature_columns
        self.label_column = label_column
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.feature_dtype = np.dtype(feature_dtype)
        self.label_dtype = np.dtype(label_dtype)
        self.prefetch = max(0, prefetch)
        self.device = device
        self.drop_last = drop_last
        self._epoch = 0
        self._columns: Optional[Dict[str, np.ndarray]] = None
        self._feat_matrix: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None

    # -- sizing ---------------------------------------------------------
    def __len__(self) -> int:
        n = self._dataset.rows_per_shard
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    @property
    def num_features(self) -> int:
        return len(self.feature_columns)

    # -- epoch iteration ------------------------------------------------
    def __iter__(self):
        epoch = self._epoch
        self._epoch += 1
        return self._epoch_iter(epoch)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def _materialize(self) -> Dict[str, np.ndarray]:
        if self._columns is None:
            wanted = list(self.feature_columns)
            if self.label_column:
                wanted.append(self.label_column)
            self._columns = self._dataset.shard_columns(self._rank, wanted)
        return self._columns

    def _stage_matrix(self):
        """Columns → ONE row-major ``[n, F]`` matrix, built once and reused
        every epoch. Batch assembly then gathers whole rows (a feature row
        is contiguous — often a single cache line) instead of hopping
        between F column arrays per row, which costs a cache miss per
        (row, column) under a shuffled permutation. Measured ~6× ingest
        bandwidth on 16-feature shuffled epochs.
        """
        if self._feat_matrix is not None:
            return self._feat_matrix, self._labels
        cols = self._materialize()
        feats = [cols[c] for c in self.feature_columns]
        n = len(feats[0])
        if self.feature_dtype in (np.dtype(np.float32), np.dtype(np.int32)):
            # Sequential pass through the native kernel.
            matrix = native.gather_matrix(
                feats, np.arange(n, dtype=np.int64),
                out_dtype=self.feature_dtype,
            )
        else:
            matrix = np.stack(
                [f.astype(self.feature_dtype, copy=False) for f in feats],
                axis=1,
            )
        labels = None
        if self.label_column:
            labels = cols[self.label_column].astype(
                self.label_dtype, copy=False
            )
        # Drop the per-column feature buffers: the matrix replaces them
        # (keeps peak memory at ~2× dataset, steady-state at ~1×).
        for c in self.feature_columns:
            cols.pop(c, None)
        self._feat_matrix, self._labels = matrix, labels
        return matrix, labels

    def _staged_batches(self, epoch: int) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        matrix, labels = self._stage_matrix()
        n = matrix.shape[0]
        order = None
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch * 1009 + self._rank)
            order = rng.permutation(n)
        n_batches = len(self)
        # Hoisted out of the hot loop: meter() takes the registry lock.
        rows_meter = metrics.meter("ingest/rows")
        bytes_meter = metrics.meter("ingest/bytes")
        for b in range(n_batches):
            lo = b * self.batch_size
            hi = min(lo + self.batch_size, n)
            if lo >= hi:
                break
            if order is None:
                # Sequential epoch: zero-copy row-slice views.
                x = matrix[lo:hi]
                y = labels[lo:hi] if labels is not None else None
            else:
                idx = order[lo:hi]
                x = native.gather_rows(matrix, idx)
                y = labels[idx] if labels is not None else None
            metrics.counter_add("ingest/batches")
            rows_meter.add(hi - lo)
            bytes_meter.add(x.nbytes + (y.nbytes if y is not None else 0))
            yield x, y

    def _epoch_iter(self, epoch: int):
        import jax

        source = self._staged_batches(epoch)
        stop_event = None
        if self.prefetch > 0:
            source, stop_event = _background(source, self.prefetch)

        device = self.device

        def put(batch):
            x, y = batch
            if device is not None:
                x = jax.device_put(x, device)
                y = jax.device_put(y, device) if y is not None else None
            return (x, y) if self.label_column else x

        # Double buffer: keep one transfer in flight ahead of the consumer.
        try:
            pending = None
            for batch in source:
                staged = put(batch)
                if pending is not None:
                    yield pending
                pending = staged
            if pending is not None:
                yield pending
        finally:
            # Abandoned epoch (early break / single next()): unblock the
            # producer thread so it exits instead of leaking.
            if stop_event is not None:
                stop_event.set()


def _background(it: Iterator, depth: int):
    """Run ``it`` in a daemon thread, buffering ``depth`` items.

    Returns ``(iterator, stop_event)``; setting the event makes the
    producer drain out promptly (a full queue never blocks it forever)."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _DONE = object()
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in it:
                if not _put(item):
                    return
            _put(_DONE)
        except BaseException as exc:  # surface errors on the consumer side
            _put(exc)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()

    def consume():
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    return consume(), stop
