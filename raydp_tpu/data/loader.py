"""Host→device batch pipeline: gather, stage, prefetch, device_put.

The hot path of training ingest. Per epoch:

  1. shard columns live as contiguous numpy arrays (zero-copy from Arrow
     where dtypes allow);
  2. a permutation is drawn (epoch-seeded — reshuffle every epoch like the
     reference's per-epoch shard shuffle, dataset.py:355-376);
  3. transfer CHUNKS (``transfer_coalesce`` batches each) are assembled
     by the native row-gather kernel (raydp_tpu/native/src/gather.cpp);
  4. a background thread keeps ``prefetch`` staged chunks ahead;
  5. chunks ship with ONE ``jax.device_put`` each and up to
     ``transfer_window`` chunks stay in flight while the caller computes;
     batches are on-device slices of landed chunks.

Why chunks: a per-batch device_put pays the host↔device round trip per
batch — on a remote-tunnel TPU that RTT is ~100ms, which capped r4's
measured device feed at 0.041 GB/s while the same loader fed host arrays
at 0.76 GB/s (r4 verdict Weak #4). Coalescing N batches into one
transfer divides the RTT cost by N, and the multi-chunk window overlaps
the remaining transfers with compute; on-device slicing is free by
comparison (slices are async XLA ops that pipeline).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from raydp_tpu.native import lib as native
from raydp_tpu.telemetry import accounting as _acct
from raydp_tpu.telemetry import current_context, propagated, span
from raydp_tpu.telemetry import flight_recorder as _flight
from raydp_tpu.telemetry import overlap as _overlap
from raydp_tpu.telemetry import progress as _progress
from raydp_tpu.telemetry import watchdog as _watchdog
from raydp_tpu.utils.profiling import metrics

# Auto transfer-chunk sizing: coalesce batches until a chunk reaches this
# many bytes (or 32 batches, whichever is smaller). Sized by measurement
# on the high-latency remote-TPU link: per-device_put overhead is
# ~0.4s regardless of size, so effective bandwidth keeps climbing with
# chunk size (4MB→0.007, 32MB→0.083, 128MB→0.120, 256MB→0.133 GB/s
# measured raw); 128MB reaches ~90% of the link's asymptotic ceiling
# while bounding staging memory at window×128MB. On a local TPU-VM PCIe
# link the overhead is µs-scale and chunk size is immaterial — the env
# var RAYDP_TRANSFER_CHUNK_MB overrides for tuning.
_TARGET_CHUNK_BYTES = int(
    __import__("os").environ.get("RAYDP_TRANSFER_CHUNK_MB", 128)
) * 1024 * 1024
_MAX_COALESCE = 32


class _PackedChunk(NamedTuple):
    """Features + labels packed into ONE contiguous staging buffer.

    A labeled chunk used to pay TWO device_put round trips (features,
    then labels — on a ~100ms-RTT remote-TPU link that doubles the
    per-chunk overhead the coalescing exists to amortize). Packing both
    into a single uint8 buffer makes every chunk exactly one transfer;
    the typed views are recovered on device with zero-cost bitcasts.
    The packing memcpy happens producer-side (the staging generator /
    prefetch thread), so it overlaps the in-flight transfer window.
    """

    buf: np.ndarray  # uint8, [x.nbytes + y.nbytes]
    rows: int


def _pack_chunk(x: np.ndarray, y: np.ndarray) -> _PackedChunk:
    xb = np.ascontiguousarray(x).view(np.uint8).reshape(-1)
    yb = np.ascontiguousarray(y).view(np.uint8).reshape(-1)
    return _PackedChunk(np.concatenate([xb, yb]), x.shape[0])


def _cut_rows(bufs: List[np.ndarray], lo: int, hi: int) -> np.ndarray:
    """Rows [lo, hi) of the logical concatenation of ``bufs`` — a view
    when the cut stays inside one buffer, a copy when it spans two."""
    out = []
    pos = 0
    for b in bufs:
        n = len(b)
        if pos + n <= lo:
            pos += n
            continue
        if pos >= hi:
            break
        out.append(b[max(0, lo - pos):min(n, hi - pos)])
        pos += n
    return out[0] if len(out) == 1 else np.concatenate(out)


class JaxShardLoader:
    """Iterable over (features, labels) device arrays for one shard.

    Re-iterable: each ``iter()`` is a new epoch with a fresh permutation.
    """

    def __init__(
        self,
        dataset,
        rank: int,
        feature_columns: List[str],
        label_column: Optional[str],
        batch_size: int,
        shuffle: bool,
        seed: int,
        feature_dtype,
        label_dtype,
        prefetch: int,
        device,
        drop_last: bool,
        transfer_coalesce: Optional[int] = None,
        transfer_window: int = 2,
    ):
        self._dataset = dataset
        self._rank = rank
        self.feature_columns = feature_columns
        self.label_column = label_column
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.feature_dtype = np.dtype(feature_dtype)
        self.label_dtype = np.dtype(label_dtype)
        self.prefetch = max(0, prefetch)
        self.device = device
        self.drop_last = drop_last
        # None = auto-size chunks to ~_TARGET_CHUNK_BYTES; 1 = one
        # device_put per batch (the pre-r5 behavior, kept measurable for
        # the bench's micro-batch row).
        self.transfer_coalesce = transfer_coalesce
        self.transfer_window = max(1, transfer_window)
        self._epoch = 0
        self._columns: Optional[Dict[str, np.ndarray]] = None
        self._feat_matrix: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None

    # -- sizing ---------------------------------------------------------
    def __len__(self) -> int:
        n = self._dataset.rows_per_shard
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    @property
    def num_features(self) -> int:
        return len(self.feature_columns)

    # -- epoch iteration ------------------------------------------------
    def __iter__(self):
        epoch = self._epoch
        self._epoch += 1
        # Workload-root attribution: an epoch driven with no ambient
        # JobContext (bare loader benchmarks) installs one process
        # default so its ingest usage still bills somewhere findable.
        if _acct.current_job() is None:
            _acct.set_process_job(_acct.mint_job("loader"))
        return self._epoch_iter(epoch)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def _materialize(self) -> Dict[str, np.ndarray]:
        if self._columns is None:
            wanted = list(self.feature_columns)
            if self.label_column:
                wanted.append(self.label_column)
            self._columns = self._dataset.shard_columns(self._rank, wanted)
        return self._columns

    def _stage_matrix(self):
        """Columns → ONE row-major ``[n, F]`` matrix, built once and reused
        every epoch. Batch assembly then gathers whole rows (a feature row
        is contiguous — often a single cache line) instead of hopping
        between F column arrays per row, which costs a cache miss per
        (row, column) under a shuffled permutation. Measured ~6× ingest
        bandwidth on 16-feature shuffled epochs.
        """
        if self._feat_matrix is not None:
            return self._feat_matrix, self._labels
        cols = self._materialize()
        feats = [cols[c] for c in self.feature_columns]
        n = len(feats[0])
        with span("ingest/stage_matrix", rank=self._rank, rows=n,
                  features=len(feats)):
            if self.feature_dtype in (np.dtype(np.float32),
                                      np.dtype(np.int32)):
                # Sequential pass through the native kernel.
                matrix = native.gather_matrix(
                    feats, np.arange(n, dtype=np.int64),
                    out_dtype=self.feature_dtype,
                )
            else:
                matrix = np.stack(
                    [f.astype(self.feature_dtype, copy=False) for f in feats],
                    axis=1,
                )
        labels = None
        if self.label_column:
            labels = cols[self.label_column].astype(
                self.label_dtype, copy=False
            )
        # Drop the per-column feature buffers: the matrix replaces them
        # (keeps peak memory at ~2× dataset, steady-state at ~1×).
        for c in self.feature_columns:
            cols.pop(c, None)
        self._feat_matrix, self._labels = matrix, labels
        _acct.add_usage(
            _acct.STAGED_BYTES,
            matrix.nbytes + (labels.nbytes if labels is not None else 0),
        )
        return matrix, labels

    def _coalesce_batches(self) -> int:
        """Batches per transfer chunk. Explicit setting ALWAYS wins —
        including on the host path (device None), where a caller may want
        bigger gather chunks for cache efficiency. Auto (None) sizes
        chunks toward ``_TARGET_CHUNK_BYTES`` capped at ``_MAX_COALESCE``;
        host-path auto stays at 1: there is no transfer to amortize and
        per-batch granularity keeps prefetch memory small."""
        if self.transfer_coalesce is not None:
            return max(1, self.transfer_coalesce)
        if self.device is None:
            return 1
        row_bytes = (
            self.num_features * self.feature_dtype.itemsize
            + (self.label_dtype.itemsize if self.label_column else 0)
        )
        batch_bytes = max(1, self.batch_size * row_bytes)
        return int(
            min(_MAX_COALESCE, max(1, _TARGET_CHUNK_BYTES // batch_bytes))
        )

    def _staged_chunks(
        self, epoch: int, rows_per_chunk: int, pack: bool = False,
        start_row: int = 0,
    ) -> Iterator:
        """Gather the epoch's rows in ``rows_per_chunk`` pieces (a chunk
        is ``transfer_coalesce`` batches; 1 batch on the host path).

        ``pack=True`` (device path with labels): each chunk is emitted as
        a :class:`_PackedChunk` — features and labels in one staging
        buffer — so the consumer ships it with a single device_put. The
        pack memcpy runs HERE, on the producer side, overlapping the
        consumer's in-flight transfers.

        ``start_row`` (chunk-aligned) skips rows the epoch-0 prefix
        streamer already served — this generator finishes the epoch from
        there."""
        matrix, labels = self._stage_matrix()
        n = matrix.shape[0]
        order = None
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch * 1009 + self._rank)
            order = rng.permutation(n)
        # Rows the epoch actually serves (drop_last trims the ragged
        # batch tail).
        n_used = min(n, len(self) * self.batch_size)
        # Hoisted out of the hot loop: meter() takes the registry lock.
        rows_meter = metrics.meter("ingest/rows")
        bytes_meter = metrics.meter("ingest/bytes")
        # Ingest shows up in /debug/progress like any plan stage: one
        # stage per epoch, one task per transfer chunk.
        remaining = max(0, n_used - start_row)
        n_chunks = max(1, -(-remaining // rows_per_chunk)) if remaining else 0
        prog_id = _progress.stage_store.next_id()
        _progress.progress.stage_begin(
            prog_id, f"ingest[epoch {epoch}]", n_chunks
        )
        try:
            yield from self._chunk_iter(
                epoch, rows_per_chunk, pack, matrix, labels, order, n_used,
                rows_meter, bytes_meter, prog_id, start_row,
            )
        finally:
            # finally (not loop-end): a consumer that stops early —
            # drop_last, a broken epoch, estimator teardown — closes
            # the generator, and the stage must not stay "active" in
            # /debug/progress forever.
            _progress.progress.stage_end(prog_id)

    def _chunk_iter(self, epoch, rows_per_chunk, pack, matrix, labels,
                    order, n_used, rows_meter, bytes_meter, prog_id,
                    start_row=0):
        for lo in range(start_row, n_used, rows_per_chunk):
            hi = min(lo + rows_per_chunk, n_used)
            # The span closes before the yield: a suspended generator must
            # not hold an open span on this thread's stack while consumer
            # code (steps, other chunks) runs and parents under it.
            # Same close-before-yield rule for the watchdog bracket: an
            # in-flight op must cover only the gather, not the
            # generator's suspension (which can legitimately last a full
            # step and would read as an ingest stall).
            with _watchdog.inflight("ingest/chunk", epoch=epoch,
                                    rank=self._rank), \
                 span("ingest/chunk", epoch=epoch, rank=self._rank,
                      rows=hi - lo):
                if order is None:
                    # Sequential epoch: zero-copy row-slice views.
                    x = matrix[lo:hi]
                    y = labels[lo:hi] if labels is not None else None
                else:
                    idx = order[lo:hi]
                    x = native.gather_rows(matrix, idx)
                    y = labels[idx] if labels is not None else None
                rows_meter.add(hi - lo)
                bytes_meter.add(
                    x.nbytes + (y.nbytes if y is not None else 0)
                )
                chunk = (
                    _pack_chunk(x, y) if pack and y is not None else (x, y)
                )
            _flight.record("loader", "chunk", epoch=epoch, rank=self._rank,
                           rows=hi - lo)
            _progress.progress.task_done(prog_id)
            yield chunk
        _progress.progress.stage_end(prog_id)

    def _stage_block(self, table) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """One landed block → (feature matrix piece, labels piece), with
        the same dtype pipeline as :meth:`_stage_matrix` so the streamed
        prefix is bit-identical to the barriered epoch."""
        feats = [
            table.column(c).to_numpy(zero_copy_only=False)
            for c in self.feature_columns
        ]
        n = table.num_rows
        if self.feature_dtype in (np.dtype(np.float32), np.dtype(np.int32)):
            m = native.gather_matrix(
                feats, np.arange(n, dtype=np.int64),
                out_dtype=self.feature_dtype,
            )
        else:
            m = np.stack(
                [f.astype(self.feature_dtype, copy=False) for f in feats],
                axis=1,
            )
        y = None
        if self.label_column:
            y = table.column(self.label_column).to_numpy(
                zero_copy_only=False
            ).astype(self.label_dtype, copy=False)
        return m, y

    def _streaming_chunks(
        self, epoch: int, rows_per_chunk: int, pack: bool
    ) -> Iterator:
        """Epoch-0 prefix streamer: start serving batches while LATE ETL
        partitions are still being produced.

        Only valid for rank 0 of an unshuffled epoch over an unshuffled
        dataset: ``divide_blocks`` hands rank 0 the dataset prefix
        ``[0, ceil(total/num_shards))``, so rows staged from the first
        landed blocks ARE the head of this shard. ``known_rows()`` is a
        monotone lower bound of ``total_rows``, hence
        ``ceil(known/num_shards)`` never overshoots the shard end — whole
        chunks below that bound are safe to emit before the plan exists.
        Once every block has landed, the remainder of the epoch (and the
        reusable epoch-1+ matrix) is delegated to :meth:`_staged_chunks`
        with ``start_row`` pointing past what was already served."""
        ds = self._dataset
        shards = ds.num_shards
        bs = self.batch_size
        rows_meter = metrics.meter("ingest/rows")
        bytes_meter = metrics.meter("ingest/bytes")
        prog_id = _progress.stage_store.next_id()
        _progress.progress.stage_begin(
            prog_id, f"ingest[epoch {epoch} prefix]", 0
        )
        feat_bufs: List[np.ndarray] = []
        label_bufs: List[np.ndarray] = []
        staged = 0  # dataset-prefix rows staged into the buffers
        emitted = 0  # rows already yielded
        try:
            for _idx, table in ds.iter_prefix_tables():
                # Staging a landed block is ingest work that overlaps the
                # still-running ETL tail — the overlap counter's bread
                # and butter.
                with _overlap.tracker.ingest(), \
                     span("ingest/stream_block", rank=self._rank,
                          rows=table.num_rows):
                    m, y = self._stage_block(table)
                feat_bufs.append(m)
                if y is not None:
                    label_bufs.append(y)
                staged += table.num_rows
                known, complete = ds.known_rows()
                if complete:
                    break
                bound = min(staged, -(-known // shards))
                bound -= bound % bs  # batch-aligned (drop_last-safe)
                while emitted + rows_per_chunk <= bound:
                    hi = emitted + rows_per_chunk
                    with _watchdog.inflight("ingest/chunk", epoch=epoch,
                                            rank=self._rank), \
                         span("ingest/chunk", epoch=epoch, rank=self._rank,
                              rows=rows_per_chunk, streamed=True):
                        x = _cut_rows(feat_bufs, emitted, hi)
                        yc = (
                            _cut_rows(label_bufs, emitted, hi)
                            if label_bufs else None
                        )
                        rows_meter.add(rows_per_chunk)
                        bytes_meter.add(
                            x.nbytes + (yc.nbytes if yc is not None else 0)
                        )
                        chunk = (
                            _pack_chunk(x, yc)
                            if pack and yc is not None else (x, yc)
                        )
                    _flight.record("loader", "chunk", epoch=epoch,
                                   rank=self._rank, rows=rows_per_chunk,
                                   streamed=True)
                    _progress.progress.task_done(prog_id)
                    emitted = hi
                    yield chunk
            metrics.counter_add("ingest/stream_prefix_rows", emitted)
        finally:
            feat_bufs.clear()
            label_bufs.clear()
            _progress.progress.stage_end(prog_id)
        # Every block has landed: finish the epoch through the normal
        # staged path (which also builds the epoch-1+ matrix).
        yield from self._staged_chunks(
            epoch, rows_per_chunk, pack, start_row=emitted
        )

    def _unpack_device(self, buf, rows: int):
        """On-device recovery of (features, labels) from one packed
        buffer: slices + reshapes + bitcasts are async XLA ops on bytes
        already resident — no further host↔device traffic."""
        from jax import lax

        nf = self.num_features
        fsz = self.feature_dtype.itemsize
        lsz = self.label_dtype.itemsize
        nb_x = rows * nf * fsz
        xb = buf[:nb_x].reshape((rows, nf, fsz) if fsz > 1 else (rows, nf))
        x = lax.bitcast_convert_type(xb, self.feature_dtype)
        yb = buf[nb_x:nb_x + rows * lsz]
        if lsz > 1:
            yb = yb.reshape((rows, lsz))
        y = lax.bitcast_convert_type(yb, self.label_dtype)
        return x, y

    def _epoch_iter(self, epoch: int):
        import jax

        bs = self.batch_size
        chunk_batches = self._coalesce_batches()
        device = self.device
        # Labeled device chunks are packed producer-side so each chunk is
        # exactly ONE device_put (unlabeled chunks already are).
        pack = device is not None and self.label_column is not None
        source = None
        if (
            epoch == 0
            and self._rank == 0
            and not self.shuffle
            and self._feat_matrix is None
        ):
            ds = self._dataset
            if (
                hasattr(ds, "has_pending_blocks")
                and not getattr(ds, "shuffle", False)
                and getattr(ds, "rank_nodes", None) is None
                and ds.has_pending_blocks()
            ):
                from raydp_tpu.dataframe.scheduler import streaming_enabled

                if streaming_enabled():
                    source = self._streaming_chunks(
                        epoch, chunk_batches * bs, pack
                    )
        if source is None:
            source = self._staged_chunks(epoch, chunk_batches * bs, pack=pack)
        stop_event = None
        if self.prefetch > 0:
            # prefetch counts CHUNKS: with coalescing the host-side
            # staging holds at most prefetch × chunk bytes.
            source, stop_event = _background(source, self.prefetch)

        batch_counter = metrics.counter_add

        def put_chunk(chunk):
            if isinstance(chunk, _PackedChunk):
                # Bracketed: a host→device transfer that never completes
                # (remote-TPU link wedge) is a classic silent hang.
                with _overlap.tracker.ingest(), \
                     _watchdog.inflight("ingest/device_put",
                                        rank=self._rank):
                    buf = jax.device_put(chunk.buf, device)
                batch_counter("ingest/device_puts")
                return self._unpack_device(buf, chunk.rows)
            x, y = chunk
            if device is not None:
                with _overlap.tracker.ingest(), \
                     _watchdog.inflight("ingest/device_put",
                                        rank=self._rank):
                    x = jax.device_put(x, device)
                    y = jax.device_put(y, device) if y is not None else None
                batch_counter(
                    "ingest/device_puts", 1 if y is None else 2
                )
            return x, y

        def batches_of(chunk):
            x, y = chunk
            n = x.shape[0] if hasattr(x, "shape") else len(x)
            for lo in range(0, n, bs):
                hi = min(lo + bs, n)
                batch_counter("ingest/batches")
                # On-device slicing: an async XLA slice per batch, which
                # pipelines behind the chunk transfer instead of paying a
                # host→device trip per batch.
                xb = x[lo:hi]
                yb = y[lo:hi] if y is not None else None
                yield (xb, yb) if self.label_column else xb

        # Transfer window: keep up to ``transfer_window`` chunk transfers
        # in flight ahead of the consumer (double buffering generalized —
        # the consumer drains batches of chunk i while chunks i+1..i+W
        # are still shipping).
        window: deque = deque()
        try:
            for chunk in source:
                window.append(put_chunk(chunk))
                if len(window) > self.transfer_window:
                    yield from batches_of(window.popleft())
            while window:
                yield from batches_of(window.popleft())
        finally:
            # Abandoned epoch (early break / single next()): unblock the
            # producer thread so it exits instead of leaking.
            if stop_event is not None:
                stop_event.set()


def _background(it: Iterator, depth: int):
    """Run ``it`` in a daemon thread, buffering ``depth`` items.

    Returns ``(iterator, stop_event)``; setting the event makes the
    producer drain out promptly (a full queue never blocks it forever).

    The consumer's trace context is captured HERE (typically inside the
    epoch span) and installed on the producer thread, so the
    ``ingest/*`` spans it records nest in the training trace instead of
    starting a fresh one per epoch."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _DONE = object()
    stop = threading.Event()
    # Producer errors surface PROMPTLY through this side channel: queueing
    # the exception behind ``depth`` buffered items would make the
    # consumer drain stale chunks first and report the failure a full
    # prefetch window late.
    err: List[BaseException] = []
    trace_ctx = current_context()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        with propagated(trace_ctx):
            try:
                for item in it:
                    if not _put(item):
                        return
                _put(_DONE)
            except BaseException as exc:  # surface errors on consumer side
                err.append(exc)
                # Wake a consumer blocked on an empty queue; a full one
                # means it will hit the err check on its next pull.
                _put(_DONE)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()

    def consume():
        # Consumer-side starvation is the input-wait half of the step
        # phase model: every second spent blocked here is a second the
        # training loop sat idle waiting for data. The producer already
        # accounts its own pack/put time; this counter closes the gap.
        while True:
            if err:
                raise err[0]
            t0 = time.perf_counter()
            item = q.get()
            metrics.counter_add(
                "ingest/wait_seconds", time.perf_counter() - t0
            )
            if err:
                # Raced with the failure while pulling: prefer the error
                # over any still-buffered item.
                raise err[0]
            if item is _DONE:
                return
            yield item

    return consume(), stop
