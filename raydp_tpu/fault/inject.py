"""Process-local fault hooks and preemption state.

All hooks are cheap no-ops unless ``RAYDP_TPU_FAULT_PLAN`` is set, so
production paths pay one env lookup. The parsed plan is cached per
plan string; each armed clause fires at most once per process.

Preemption is a process-wide flag: both the injected ``preempt``
clause and a real SIGTERM (via :func:`install_sigterm_drain`) set it,
arm a grace-deadline force-exit timer, and let the training loop
drain the in-flight step and write an emergency checkpoint before
raising :class:`PreemptionError`. :func:`mark_drained` cancels the
force-exit timer once the emergency checkpoint is durable.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import List, Optional

from raydp_tpu.fault.plan import FAULT_PLAN_ENV, FaultClause, parse_plan
from raydp_tpu.utils import clock as _clock

PREEMPT_GRACE_ENV = "RAYDP_TPU_PREEMPT_GRACE_S"

_DEFAULT_GRACE_S = 30.0
_PREEMPT_EXIT_CODE = 143  # 128 + SIGTERM, what an undrained preemption looks like


class PreemptionError(RuntimeError):
    """Raised by a training loop after draining a preemption notice.

    ``checkpoint_path`` is the emergency checkpoint written during the
    drain, or ``None`` if no checkpoint directory was configured.
    """

    def __init__(self, message: str, checkpoint_path: Optional[str] = None):
        super().__init__(message)
        self.checkpoint_path = checkpoint_path


class SpawnFaultError(RuntimeError):
    """Raised by :func:`on_spawn` for an armed ``spawn_fail`` clause.

    The autoscaler's provisioner boundary catches it and applies the
    backoff-and-retry budget, exactly as it would a real launcher
    failure.
    """


class _State:
    def __init__(self) -> None:
        self.plan_text: Optional[str] = None
        self.clauses: List[FaultClause] = []
        self.rpc_counts: dict = {}
        self.spawn_count = 0
        self.preempt = threading.Event()
        self.drained = threading.Event()
        self.grace_timer: Optional[threading.Timer] = None
        self.prev_sigterm = None
        self.sigterm_installed = False


_lock = threading.Lock()
_state = _State()


def ambient_rank() -> Optional[int]:
    """The SPMD rank of this process, if launched as a gang member."""
    raw = os.environ.get("RAYDP_SPMD_RANK")
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _ambient_job() -> tuple:
    """``(job_id, name)`` of the job this process's work is billed to.

    Reads the accounting plane's ambient job (thread scope, process
    default, or ``RAYDP_TPU_JOB`` adoption in worker mains) so
    ``job=``-targeted clauses fire only in the right tenant. Returns
    ``(None, None)`` when no job is in scope.
    """
    try:
        from raydp_tpu.telemetry import accounting as _acct

        ctx = _acct.current_job()
        if ctx is None:
            return (None, None)
        return (ctx.job_id, ctx.name)
    except Exception:
        return (None, None)


def _clauses() -> List[FaultClause]:
    text = os.environ.get("RAYDP_TPU_FAULT_PLAN")
    if not text:
        return []
    with _lock:
        if _state.plan_text != text:
            seed_raw = os.environ.get("RAYDP_TPU_FAULT_SEED", "0")
            try:
                seed = int(seed_raw)
            except ValueError:
                seed = 0
            _state.clauses = parse_plan(text, seed=seed)
            _state.plan_text = text
            _state.rpc_counts = {}
            _state.spawn_count = 0
        return _state.clauses


def active() -> bool:
    """True when a fault plan is configured for this process."""
    return bool(os.environ.get("RAYDP_TPU_FAULT_PLAN"))


def plan_clauses() -> List[FaultClause]:
    """The active plan's parsed clauses (shared, mutable — marking one
    ``fired`` consumes it process-wide). The simulator uses this to
    honor ``serve_kill``/``latency`` clauses on virtual time with
    simulated deaths instead of the process-killing ``_die`` path."""
    return _clauses()


def _emit_clause(clause: FaultClause, what: str) -> None:
    """Timeline record of a clause firing — the injected cause lands in
    /debug/events next to the gang churn it produces. Write-through
    makes it durable even when the clause kills this process."""
    try:
        from raydp_tpu.telemetry import events as _events

        # N.B. the attr must not be named "kind" — that is emit()'s
        # first positional parameter and the call would TypeError
        # (swallowed by the except below, losing the record).
        _events.emit("fault/clause", clause=clause.kind, what=what)
    except Exception:
        pass


def _die(clause: FaultClause, what: str) -> None:
    print(
        f"raydp-fault: injected kill: {what} (exit {clause.code})",
        file=sys.stderr,
        flush=True,
    )
    _emit_clause(clause, what)
    os._exit(clause.code)


def on_train_step(step: int, rank: Optional[int] = None) -> None:
    """Hook at each estimator train-step boundary.

    ``step`` is 1-based (the step that just completed). ``rank``
    defaults to the ambient SPMD rank.
    """
    clauses = _clauses()
    if not clauses:
        return
    if rank is None:
        rank = ambient_rank()
    job_id, job_name = _ambient_job()
    for c in clauses:
        if not c.armed or c.fired:
            continue
        if not c.matches_job(job_id, job_name):
            continue
        if c.kind == "kill" and c.step is not None and c.step == step:
            if c.matches_rank(rank):
                c.fired = True
                _die(c, f"rank {rank} at train step {step}")
        elif c.kind == "preempt" and c.step == step and c.matches_rank(rank):
            c.fired = True
            _emit_clause(c, f"rank {rank} preempted at train step {step}")
            request_preemption(grace_s=c.grace)


def on_task(worker_id: str, task_index: int) -> None:
    """Hook when an ETL worker begins its ``task_index``-th task."""
    clauses = _clauses()
    if not clauses:
        return
    job_id, job_name = _ambient_job()
    for c in clauses:
        if not c.armed or c.fired:
            continue
        if not c.matches_job(job_id, job_name):
            continue
        if c.kind == "kill" and c.task is not None and c.task == task_index:
            if c.matches_worker(worker_id):
                c.fired = True
                _die(c, f"worker {worker_id} at task {task_index}")


def ambient_replica() -> Optional[int]:
    """The serving replica index of this process, if launched as one."""
    raw = os.environ.get("RAYDP_SERVE_REPLICA")
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _ambient_incarnation() -> int:
    """Restart count of this replica's lineage (0 = first spawn)."""
    try:
        return int(os.environ.get("RAYDP_SERVE_INCARNATION", "0"))
    except ValueError:
        return 0


def on_serve_request(
    request_index: int, replica: Optional[int] = None
) -> None:
    """Hook when a serving replica begins executing its
    ``request_index``-th request (0-based, per process).

    Fires ``serve_kill`` (hard-exit, first incarnation of the lineage
    only — respawned replicas are not re-killed, so self-healing is
    observable) and ``latency`` (in-place stall) clauses.
    """
    clauses = _clauses()
    if not clauses:
        return
    if replica is None:
        replica = ambient_replica()
    for c in clauses:
        if not c.armed or c.fired:
            continue
        if not c.matches_replica(replica):
            continue
        if c.kind == "serve_kill" and c.request == request_index:
            if _ambient_incarnation() > 0:
                continue
            c.fired = True
            _die(c, f"replica {replica} at request {request_index}")
        elif c.kind == "latency" and c.nth == request_index:
            c.fired = True
            _emit_clause(
                c,
                f"replica {replica} stalled {c.delay}s "
                f"at request {request_index}",
            )
            # Via the clock seam: a latency clause inside a simulation
            # stalls virtual time, not the wall.
            _clock.sleep(c.delay)


def on_rpc(qualified_method: str) -> Optional[str]:
    """Hook before an RPC client sends ``Service.Method``.

    Sleeps in place for a matching ``rpc_delay`` clause. Returns
    ``"drop"`` when a matching ``rpc_drop`` clause fires (the caller
    raises UNAVAILABLE instead of sending); ``None`` otherwise.
    """
    clauses = _clauses()
    if not clauses:
        return None
    with _lock:
        n = _state.rpc_counts.get(qualified_method, 0)
        _state.rpc_counts[qualified_method] = n + 1
    verdict = None
    for c in clauses:
        if not c.armed or c.fired or c.nth != n or not c.matches_method(qualified_method):
            continue
        if c.kind == "rpc_delay":
            c.fired = True
            _emit_clause(c, f"delayed {qualified_method} by {c.delay}s")
            time.sleep(c.delay)
        elif c.kind == "rpc_drop":
            c.fired = True
            _emit_clause(c, f"dropped {qualified_method}")
            verdict = "drop"
    return verdict


def on_spawn() -> None:
    """Hook before each host-spawn attempt at the provisioner boundary.

    Counts attempts per process (0-based). A matching ``spawn_delay``
    clause sleeps in place (hung cloud-provisioning call); a matching
    ``spawn_fail`` clause raises :class:`SpawnFaultError`, which the
    autoscaler treats as a provisioner failure to back off and retry.
    """
    clauses = _clauses()
    if not clauses:
        return
    with _lock:
        n = _state.spawn_count
        _state.spawn_count = n + 1
    for c in clauses:
        if not c.armed or c.fired or c.nth != n:
            continue
        if c.kind == "spawn_delay":
            c.fired = True
            _emit_clause(c, f"delayed spawn attempt {n} by {c.delay}s")
            # Clock seam: virtual under simulation, wall time otherwise.
            _clock.sleep(c.delay)
        elif c.kind == "spawn_fail":
            c.fired = True
            _emit_clause(c, f"failed spawn attempt {n}")
            raise SpawnFaultError(f"injected spawn failure (attempt {n})")


def on_heartbeat(
    beat_index: int, rank: Optional[int] = None, worker: Optional[str] = None
) -> bool:
    """Hook per heartbeat; returns True when this beat must be skipped."""
    clauses = _clauses()
    if not clauses:
        return False
    if rank is None and worker is None:
        rank = ambient_rank()
    for c in clauses:
        if c.kind != "hb_stall" or not c.armed:
            continue
        if c.rank is not None and not c.matches_rank(rank):
            continue
        if c.worker is not None and not c.matches_worker(worker):
            continue
        if c.after <= beat_index < c.after + c.beats:
            return True
    return False


def preemption_requested() -> bool:
    """True once a preemption notice (real or injected) has landed."""
    return _state.preempt.is_set()


def request_preemption(grace_s: Optional[float] = None) -> None:
    """Deliver a preemption notice to this process.

    Sets the drain flag and arms a force-exit timer: if the training
    loop has not called :func:`mark_drained` within the grace window,
    the process hard-exits with code 143 — exactly the budgeted
    behaviour of a real TPU preemption. ``grace_s <= 0`` disables the
    force-exit deadline (useful for in-process tests).
    """
    if grace_s is None:
        raw = os.environ.get("RAYDP_TPU_PREEMPT_GRACE_S")
        try:
            grace_s = float(raw) if raw else _DEFAULT_GRACE_S
        except ValueError:
            grace_s = _DEFAULT_GRACE_S
    with _lock:
        first = not _state.preempt.is_set()
        _state.preempt.set()
        if first and grace_s > 0:
            def _force_exit() -> None:
                if _state.drained.is_set():
                    return
                print(
                    f"raydp-fault: preemption grace of {grace_s:.1f}s expired "
                    "before drain; force-exiting",
                    file=sys.stderr,
                    flush=True,
                )
                os._exit(_PREEMPT_EXIT_CODE)

            t = threading.Timer(grace_s, _force_exit)
            t.daemon = True
            t.start()
            _state.grace_timer = t
    if first:
        print(
            f"raydp-fault: preemption notice (grace {grace_s:.1f}s); "
            "draining step and writing emergency checkpoint",
            file=sys.stderr,
            flush=True,
        )


def mark_drained() -> None:
    """Cancel the preemption force-exit deadline; drain completed."""
    _state.drained.set()
    with _lock:
        if _state.grace_timer is not None:
            _state.grace_timer.cancel()
            _state.grace_timer = None


def install_sigterm_drain() -> None:
    """Route SIGTERM into the preemption drain path.

    Must run *after* any flight-recorder signal install so the drain
    handler (checkpoint-then-exit) replaces the dump-then-die default.
    No-op off the main thread and on platforms without SIGTERM.
    """
    def _handler(signum, frame):  # noqa: ANN001 - signal signature
        request_preemption()

    try:
        with _lock:
            if _state.sigterm_installed:
                return
            _state.prev_sigterm = signal.signal(signal.SIGTERM, _handler)
            _state.sigterm_installed = True
    except ValueError:
        # Not the main thread; preemption notices must then be injected.
        pass


def reset_for_tests() -> None:
    """Clear all process-local fault state (plan cache, preemption)."""
    with _lock:
        _state.plan_text = None
        _state.clauses = []
        _state.rpc_counts = {}
        _state.spawn_count = 0
        _state.preempt = threading.Event()
        _state.drained = threading.Event()
        if _state.grace_timer is not None:
            _state.grace_timer.cancel()
            _state.grace_timer = None
        if _state.sigterm_installed and _state.prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, _state.prev_sigterm)
            except ValueError:
                pass
        _state.sigterm_installed = False
        _state.prev_sigterm = None
