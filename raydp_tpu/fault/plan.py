"""Fault-plan grammar and parser.

A plan is a semicolon-separated list of clauses read from
``RAYDP_TPU_FAULT_PLAN``::

    clause  ::= kind ":" key "=" value ("," key "=" value)*
    plan    ::= clause (";" clause)*

Kinds and their keys (see ``doc/fault_tolerance.md`` for semantics):

``kill``
    ``rank=N,step=K[,code=C]`` — SPMD rank ``N`` hard-exits with code
    ``C`` (default 23) when its estimator reaches train step ``K``; or
    ``worker=ID,task=K[,code=C]`` — ETL worker ``ID`` hard-exits when
    it starts its ``K``-th task (0-based). Either form may target
    ``job=NAME`` instead of (or in addition to) ``rank``/``worker``:
    the clause then only fires in a process whose ambient job
    (``RAYDP_TPU_JOB`` propagation) has that name or job id — the
    multi-tenant analogue of rank targeting.
``preempt``
    ``step=K[,rank=N][,job=NAME][,grace=S]`` — deliver a preemption
    notice at train step ``K`` (all ranks unless ``rank`` is given;
    injected slice preemption takes the whole gang, matching TPU
    semantics). ``job=NAME`` restricts the notice to gangs of that
    job, so a chaos sweep over a shared cluster preempts one tenant
    deterministically. ``grace`` overrides
    ``RAYDP_TPU_PREEMPT_GRACE_S`` for the force-exit deadline.
``rpc_delay``
    ``method=M,nth=K,delay=S`` — the ``K``-th (0-based) client call of
    RPC method ``M`` (bare or ``Service.Method``) sleeps ``S`` seconds
    before sending.
``rpc_drop``
    ``method=M,nth=K`` — the ``K``-th client call of method ``M``
    raises an UNAVAILABLE error instead of being sent.
``hb_stall``
    ``rank=N,beats=B[,after=K]`` (or ``worker=ID``) — the heartbeat
    loop of that process skips ``B`` consecutive beats starting at
    beat ``K`` (default 0), simulating a network partition long enough
    to trip liveness timeouts.
``serve_kill``
    ``replica=N,request=K[,code=C]`` — serving replica ``N`` hard-exits
    with code ``C`` (default 23) when it begins executing its ``K``-th
    request (0-based, counted per process). The clause targets the
    lineage's *first* incarnation only: a respawned replica is not
    re-killed, mirroring how a ``kill step=K`` fires once because the
    resumed run skips past step ``K``.
``latency``
    ``nth=K,delay=S[,replica=N]`` — the ``K``-th request executed by a
    serving replica (0-based, per process) stalls ``S`` seconds before
    running, simulating a straggler batch; ``replica=N`` restricts the
    stall to one replica.
``spawn_fail``
    ``nth=K[,prob=P]`` — the ``K``-th host-spawn attempt (0-based,
    counted per process at the autoscaler's provisioner boundary)
    raises a provisioner error instead of launching, exercising the
    backoff-and-retry budget deterministically.
``spawn_delay``
    ``nth=K,delay=S`` — the ``K``-th host-spawn attempt stalls ``S``
    seconds before proceeding, simulating a hung cloud-provisioning
    call.

Any clause may carry ``prob=P`` (0..1): whether it arms is decided
once, deterministically, from ``RAYDP_TPU_FAULT_SEED`` and the clause
index — so a seeded chaos sweep is reproducible run-to-run. Each
armed clause fires at most once per process.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_PLAN_ENV = "RAYDP_TPU_FAULT_PLAN"
FAULT_SEED_ENV = "RAYDP_TPU_FAULT_SEED"

_KINDS = (
    "kill", "preempt", "rpc_delay", "rpc_drop", "hb_stall",
    "serve_kill", "latency", "spawn_fail", "spawn_delay",
)

_REQUIRED: Dict[str, tuple] = {
    "rpc_delay": ("method", "nth", "delay"),
    "rpc_drop": ("method", "nth"),
    "hb_stall": ("beats",),
    "serve_kill": ("replica", "request"),
    "latency": ("nth", "delay"),
    "spawn_fail": ("nth",),
    "spawn_delay": ("nth", "delay"),
}

_ALLOWED: Dict[str, tuple] = {
    "kill": ("rank", "step", "worker", "task", "code", "job", "prob"),
    "preempt": ("step", "rank", "grace", "job", "prob"),
    "rpc_delay": ("method", "nth", "delay", "prob"),
    "rpc_drop": ("method", "nth", "prob"),
    "hb_stall": ("rank", "worker", "beats", "after", "prob"),
    "serve_kill": ("replica", "request", "code", "prob"),
    "latency": ("nth", "delay", "replica", "prob"),
    "spawn_fail": ("nth", "prob"),
    "spawn_delay": ("nth", "delay", "prob"),
}

_INT_KEYS = (
    "rank", "step", "task", "code", "nth", "beats", "after",
    "replica", "request",
)
_FLOAT_KEYS = ("delay", "grace", "prob")


class FaultPlanError(ValueError):
    """Raised for a malformed ``RAYDP_TPU_FAULT_PLAN`` value."""


@dataclass
class FaultClause:
    """One parsed clause of the fault plan."""

    kind: str
    rank: Optional[int] = None
    worker: Optional[str] = None
    job: Optional[str] = None
    step: Optional[int] = None
    task: Optional[int] = None
    code: int = 23
    method: Optional[str] = None
    nth: Optional[int] = None
    replica: Optional[int] = None
    request: Optional[int] = None
    delay: float = 0.0
    grace: Optional[float] = None
    beats: int = 0
    after: int = 0
    prob: float = 1.0
    armed: bool = True
    fired: bool = field(default=False, compare=False)

    def matches_rank(self, rank: Optional[int]) -> bool:
        return self.rank is None or (rank is not None and rank == self.rank)

    def matches_replica(self, replica: Optional[int]) -> bool:
        return self.replica is None or (
            replica is not None and replica == self.replica
        )

    def matches_worker(self, worker: Optional[str]) -> bool:
        return self.worker is None or (worker is not None and worker == self.worker)

    def matches_job(self, job_id: Optional[str], name: Optional[str]) -> bool:
        """True when the ambient job satisfies the ``job=`` target.

        Matches either the human name or the minted job id, so plans
        can be written before ids exist. ``job=`` with no ambient job
        never matches (a clause must not fire in unattributed work).
        """
        if self.job is None:
            return True
        return self.job in {j for j in (job_id, name) if j is not None}

    def matches_method(self, qualified: str) -> bool:
        if self.method is None:
            return False
        if self.method == qualified:
            return True
        # Bare method name matches any service ("Ping" ~ "Master.Ping").
        return "." not in self.method and qualified.rsplit(".", 1)[-1] == self.method


def _coerce(kind: str, key: str, raw: str):
    try:
        if key in _INT_KEYS:
            return int(raw)
        if key in _FLOAT_KEYS:
            return float(raw)
    except ValueError:
        raise FaultPlanError(
            f"fault plan: clause {kind!r}: key {key}={raw!r} is not numeric"
        ) from None
    return raw


def parse_plan(text: str, seed: int = 0) -> List[FaultClause]:
    """Parse a plan string into armed clauses.

    ``seed`` feeds the deterministic ``prob`` coin flips; the clause
    index is mixed in so each clause gets an independent decision.
    """
    clauses: List[FaultClause] = []
    for idx, part in enumerate(p.strip() for p in text.split(";")):
        if not part:
            continue
        kind, sep, body = part.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise FaultPlanError(
                f"fault plan: unknown kind {kind!r} (expected one of {_KINDS})"
            )
        if not sep or not body.strip():
            raise FaultPlanError(f"fault plan: clause {kind!r} has no arguments")
        kwargs: Dict[str, object] = {}
        for item in body.split(","):
            key, eq, raw = item.partition("=")
            key = key.strip()
            raw = raw.strip()
            if not eq or not key or not raw:
                raise FaultPlanError(
                    f"fault plan: clause {kind!r}: bad key=value item {item.strip()!r}"
                )
            if key not in _ALLOWED[kind]:
                raise FaultPlanError(
                    f"fault plan: clause {kind!r} does not accept key {key!r} "
                    f"(allowed: {_ALLOWED[kind]})"
                )
            if key in kwargs:
                raise FaultPlanError(
                    f"fault plan: clause {kind!r}: duplicate key {key!r}"
                )
            kwargs[key] = _coerce(kind, key, raw)
        for req in _REQUIRED.get(kind, ()):
            if req not in kwargs:
                raise FaultPlanError(
                    f"fault plan: clause {kind!r} requires key {req!r}"
                )
        if kind == "kill":
            if ("step" in kwargs) == ("task" in kwargs):
                raise FaultPlanError(
                    "fault plan: kill clause needs exactly one of step= (train "
                    "rank) or task= (ETL worker)"
                )
            if "step" in kwargs and "rank" not in kwargs and "job" not in kwargs:
                raise FaultPlanError(
                    "fault plan: kill step= clause needs rank= or job="
                )
            if "task" in kwargs and "worker" not in kwargs and "job" not in kwargs:
                raise FaultPlanError(
                    "fault plan: kill task= clause needs worker= or job="
                )
        if kind == "preempt" and "step" not in kwargs:
            raise FaultPlanError("fault plan: preempt clause requires key 'step'")
        if kind == "hb_stall" and "rank" not in kwargs and "worker" not in kwargs:
            raise FaultPlanError(
                "fault plan: hb_stall clause needs rank= or worker="
            )
        clause = FaultClause(kind=kind, **kwargs)  # type: ignore[arg-type]
        if not 0.0 <= clause.prob <= 1.0:
            raise FaultPlanError(
                f"fault plan: clause {kind!r}: prob must be in [0, 1]"
            )
        if clause.prob < 1.0:
            # str seed: hashlib-based, stable across processes and
            # PYTHONHASHSEED (tuple seeding is hash-based + deprecated)
            clause.armed = (
                random.Random(f"{seed}:{idx}").random() < clause.prob
            )
        clauses.append(clause)
    return clauses
