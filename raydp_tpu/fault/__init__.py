"""Deterministic fault injection + preemption notices.

The fault plane that makes every recovery path in this repo testable:
a seeded, env-driven plan (``RAYDP_TPU_FAULT_PLAN``) describes exactly
which process dies, stalls, or loses an RPC, and when — so tier-1 tests
and the ``fault_tolerance`` bench section exercise rank death, host
preemption, dropped control-plane traffic, and heartbeat stalls
deterministically instead of by hope. See ``doc/fault_tolerance.md``
for the grammar and the supervisor semantics built on top.

Hook surface (all no-ops when no plan is configured):

* :func:`on_train_step` — estimator step boundary (kill / preempt).
* :func:`on_task` — ETL worker task boundary (kill).
* :func:`on_rpc` — RPC client send (delay / drop one call).
* :func:`on_heartbeat` — heartbeat loops (skip beats).
* :func:`on_serve_request` — serving replica request boundary
  (serve_kill / latency).

Preemption notices are first-class and independent of the plan: a real
SIGTERM lands in the same :func:`preemption_requested` flag the
injected ``preempt`` clause sets, so the estimator's drain-and-
emergency-checkpoint path is identical for simulated and real
preemptions.
"""
from raydp_tpu.fault.plan import (
    FAULT_PLAN_ENV,
    FAULT_SEED_ENV,
    FaultClause,
    FaultPlanError,
    parse_plan,
)
from raydp_tpu.fault.inject import (
    PREEMPT_GRACE_ENV,
    PreemptionError,
    active,
    ambient_rank,
    ambient_replica,
    install_sigterm_drain,
    mark_drained,
    on_heartbeat,
    on_rpc,
    on_serve_request,
    on_task,
    on_train_step,
    preemption_requested,
    request_preemption,
    reset_for_tests,
)

__all__ = [
    "FAULT_PLAN_ENV",
    "FAULT_SEED_ENV",
    "PREEMPT_GRACE_ENV",
    "FaultClause",
    "FaultPlanError",
    "PreemptionError",
    "active",
    "ambient_rank",
    "ambient_replica",
    "install_sigterm_drain",
    "mark_drained",
    "on_heartbeat",
    "on_rpc",
    "on_serve_request",
    "on_task",
    "on_train_step",
    "parse_plan",
    "preemption_requested",
    "request_preemption",
    "reset_for_tests",
]
