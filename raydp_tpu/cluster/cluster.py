"""Cluster facade: AppMaster + worker-pool lifecycle + task submission.

Collapses the reference's Python/JVM control-plane sandwich
(reference: python/raydp/spark/ray_cluster.py:30-97 SparkCluster,
ray_cluster_master.py:36-196 RayDPSparkMaster spawning a JVM via py4j)
into one component: the AppMaster runs in-process, workers are spawned as
subprocesses of this driver, and everything speaks one gRPC protocol.

Dynamic allocation parity (reference:
RayCoarseGrainedSchedulerBackend.scala:219-242
doRequestTotalExecutors/doKillExecutors): ``request_workers`` /
``kill_worker`` grow and shrink the pool; shm objects survive worker
death when holder-owned (the external-shuffle-service capability —
shuffle state outliving executors — reference C16).
"""
from __future__ import annotations

import itertools
import logging
import os
import secrets
import subprocess
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from raydp_tpu.cluster import placement as pl
from raydp_tpu.cluster.launcher import LaunchSpec, LocalLauncher, WorkerLauncher
from raydp_tpu.cluster.master import AppMaster, WorkerInfo
from raydp_tpu.cluster.rpc import RpcClient, RpcError
from raydp_tpu.config import ClusterConfig
from raydp_tpu.store.object_store import DEFAULT_NODE

logger = logging.getLogger(__name__)


class ClusterError(RuntimeError):
    pass


@dataclass
class TaskSpec:
    """One task in a :meth:`Cluster.submit_batch` call.

    ``data_args`` are Arrow tables that travel the DATA plane: they are
    written to the submitter's shm store and only their ObjectRefs ride
    the RPC envelope; the worker resolves them (zero-copy when
    co-located) and appends the tables after ``args`` in the call.
    """

    fn: Callable
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    worker_id: Optional[str] = None  # locality preference, not a pin
    data_args: Tuple = ()
    # Node-level placement hint: when the preferred worker is gone (or
    # none was named), any alive worker on this node still gets the
    # zero-copy shm reads the hint was chosen for (shuffle merge
    # placement). Softer than worker_id, harder than round-robin.
    node_id: Optional[str] = None


class _WorkerGone(Exception):
    """Batch envelope lost to worker death; tasks are retriable."""


#: Sentinel outcome: the envelope thread already resolved its futures
#: inline (per-envelope streaming) — nothing left for the joiner to do.
_BATCH_RESOLVED = object()


class Cluster:
    def __init__(self, config: ClusterConfig):
        self.config = config
        self.namespace = f"{_slug(config.app_name)}-{secrets.token_hex(3)}"
        self.master: Optional[AppMaster] = None
        self.pg: Optional[pl.PlacementGroup] = None
        self.launcher: WorkerLauncher = config.launcher or LocalLauncher()
        self._procs: Dict[str, subprocess.Popen] = {}
        self._worker_nodes: Dict[str, str] = {}
        self._agent_procs: Dict[str, subprocess.Popen] = {}
        self._worker_clients: Dict[str, RpcClient] = {}
        self._worker_seq = itertools.count()
        self._rr = itertools.count()  # round-robin task cursor
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(max_workers=32)
        self._resolver = None
        self._restarts_used = 0
        # Per-worker-lineage restart timestamps (monotonic) for the
        # sliding-window budget; a respawned worker inherits its
        # predecessor's list so a crash-looping worker exhausts its OWN
        # budget without starving respawns of healthy workers.
        self._restart_history: Dict[str, List[float]] = {}
        self._elastic_stop = threading.Event()
        self._elastic_thread: Optional[threading.Thread] = None
        self._trace_ctx = None
        self._metrics_server = None
        self._ts_sampler = None
        self._slo_engine = None
        self._log_dir = os.path.join(
            "/tmp/raydp_tpu", f"{_slug(config.app_name)}-{os.getpid()}"
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        os.makedirs(self._log_dir, exist_ok=True)
        # Job-level trace root: every span recorded anywhere in this
        # cluster — driver threads, master handlers, worker processes —
        # parents under this context, so a whole job merges into ONE
        # trace (workers inherit it via RAYDP_TPU_TRACEPARENT in their
        # launch env, driver threads via the process context).
        from raydp_tpu.telemetry import propagation as _prop

        self._trace_ctx = _prop.mint_context(
            "cluster/job",
            app=self.config.app_name,
            namespace=self.namespace,
        )
        _prop.set_process_context(self._trace_ctx)
        # Health plane: arm the driver's flight recorder (no signal
        # handlers — the driver is the USER's process), structured log
        # shard, and progress watchdog.
        from raydp_tpu.telemetry import flight_recorder as _flight
        from raydp_tpu.telemetry import logs as _logs
        from raydp_tpu.telemetry import watchdog as _watchdog

        _flight.install(component="driver", signals=False)
        _logs.install()
        _watchdog.ensure_started()
        _flight.record("state", "cluster_start", namespace=self.namespace,
                       app=self.config.app_name,
                       num_workers=self.config.num_workers)
        nodes = (
            pl.detect_nodes(self.config.num_virtual_nodes)
            if self.config.num_virtual_nodes
            else None
        )
        self.master = AppMaster(
            self.namespace,
            nodes=nodes,
            bind_host=self.config.bind_host,
            advertise_host=self.config.advertise_host,
            port=self.config.master_port,
        )
        try:
            self._place_group()
            self._spawn_agents()
            self.master.expect_workers(self.config.num_workers)
            for _ in range(self.config.num_workers):
                self._spawn_worker()
            if self.config.num_workers and not self.master.wait_for_workers(60.0):
                raise ClusterError(
                    f"workers failed to register within 60s "
                    f"(logs: {self._log_dir})"
                )
        except BaseException:
            # Partial start must not leak the master server/monitor thread.
            self.shutdown(del_obj_holder=True)
            raise
        logger.info(
            "cluster %s up: %d workers, master @ %s",
            self.namespace,
            self.config.num_workers,
            self.master.address,
        )
        self._elastic_thread = threading.Thread(
            target=self._elastic_loop, name="raydp-elastic", daemon=True
        )
        self._elastic_thread.start()
        self._warm_workers_async()
        self._serve_metrics()
        self._start_observability()

    def _serve_metrics(self) -> None:
        """Expose the merged Prometheus view at ``/metrics`` when
        RAYDP_TPU_METRICS_PORT is set (the k8s manifests' scrape
        target). Best-effort: a taken port must not fail cluster start."""
        from raydp_tpu.telemetry import METRICS_PORT_ENV, serve_prometheus

        port = os.environ.get(METRICS_PORT_ENV)
        if not port:
            return
        try:
            self._metrics_server = serve_prometheus(
                self.prometheus_metrics, int(port),
                progress=self.progress_report,
                # /debug/profile?seconds=N → cluster-wide gang capture,
                # not just the driver process.
                profile=lambda seconds: self.capture_profile(seconds) or {},
                # /debug/dashboard → the merged flywheel view, not just
                # the driver registry.
                dashboard=self.dashboard_report,
            )
            logger.info(
                "prometheus scrape endpoint on :%d/metrics",
                self._metrics_server.port,
            )
        except Exception:
            logger.exception("metrics endpoint failed to start")

    def _start_observability(self) -> None:
        """Arm the driver-side time-series sampler over the merged view
        and the SLO engine over its store. Both are kill-switched
        (``RAYDP_TPU_TIMESERIES=0`` / ``RAYDP_TPU_SLO=0``) and cheap:
        one snapshot fold per sampling interval. Best-effort — the
        observability plane must never fail cluster start."""
        from raydp_tpu.telemetry import slo as _slo
        from raydp_tpu.telemetry import timeseries as _ts

        try:
            if _ts.timeseries_enabled():
                self._ts_sampler = _ts.TimeSeriesSampler(
                    snapshot_fn=self.metrics_snapshot
                ).start()
            if _slo.slo_enabled() and self._ts_sampler is not None:
                self._slo_engine = _slo.SloEngine(
                    store=self._ts_sampler.store
                ).start()
        except Exception:  # pragma: no cover - observer, never fatal
            logger.exception("observability plane failed to start")

    def _warm_workers_async(self) -> None:
        """Pre-import the ETL stack on every worker in the background.

        A worker's first dataframe task otherwise pays the pandas/pyarrow
        import chain inside the first query (hundreds of ms, multiplied
        when all workers cold-start concurrently on a small host). Fire-
        and-forget: results are dropped, failures are harmless (a dead
        worker surfaces through the elastic loop, not here)."""

        def _warm(ctx):
            import pandas  # noqa: F401

            import raydp_tpu.dataframe.dataframe  # noqa: F401

            return True

        def _fire():
            try:
                for w in self.alive_workers():
                    self.submit_async(_warm, worker_id=w.worker_id)
            except Exception:  # pragma: no cover - warmup is best-effort
                pass

        threading.Thread(
            target=_fire, name="raydp-warmup", daemon=True
        ).start()

    def _elastic_loop(self) -> None:
        """Crash recovery (reference: executor reschedule on disconnect,
        RayAppMaster.scala:184-186 + schedule() re-request): a worker
        process that EXITS without being stopped by us is marked dead and
        respawned on its node. Intentional stops pop the proc from
        ``_procs`` first, so they never trip this.

        The restart budget is a PER-WORKER sliding window:
        ``max_worker_restarts`` restarts within
        ``RAYDP_TPU_RESTART_WINDOW_S`` seconds (default 600), tracked
        per lineage — the respawn inherits its predecessor's history.
        A crash-looping worker burns through its own window and stays
        down; an unrelated healthy worker that crashes later still gets
        its full budget (a global counter would have starved it).
        Restarts are exported as ``raydp_worker_restarts_total{worker}``.
        """
        from raydp_tpu.utils.profiling import metrics as _metrics

        window_s = 600.0
        raw = os.environ.get("RAYDP_TPU_RESTART_WINDOW_S")
        if raw:
            try:
                window_s = float(raw)
            except ValueError:
                pass
        while not self._elastic_stop.wait(0.5):
            with self._lock:
                exited = [
                    (wid, proc)
                    for wid, proc in self._procs.items()
                    if proc.poll() is not None
                ]
            for wid, proc in exited:
                with self._lock:
                    if self._procs.get(wid) is not proc:
                        continue  # stopped/replaced concurrently
                    self._procs.pop(wid, None)
                    node = self._worker_nodes.get(wid)
                    now = time.monotonic()
                    history = self._restart_history.setdefault(wid, [])
                    history[:] = [t for t in history if now - t < window_s]
                    allow = len(history) < self.config.max_worker_restarts
                    if allow:
                        history.append(now)
                        self._restarts_used += 1
                if self.master is None:
                    return
                from raydp_tpu.telemetry import events as _events

                _events.emit(
                    "worker/dead", worker=wid, node=node,
                    rc=proc.returncode,
                )
                self.master.mark_worker_dead(
                    wid, reason=f"process exited rc={proc.returncode}"
                )
                if allow:
                    _metrics.counter_add(f"worker_restarts/{wid}")
                    new_id = self._spawn_worker(node_id=node)
                    _events.emit(
                        "worker/restart", worker=wid, respawned_as=new_id,
                        node=node, restarts_in_window=len(history),
                    )
                    with self._lock:
                        # Lineage carry-over: if the respawn crash-loops,
                        # it exhausts this same window, not a fresh one.
                        self._restart_history[new_id] = history
                    logger.warning(
                        "worker %s crashed (rc=%s); respawned as %s on %s "
                        "(%d/%d restarts in window)",
                        wid, proc.returncode, new_id, node,
                        len(history), self.config.max_worker_restarts,
                    )
                else:
                    logger.error(
                        "worker %s crashed; its restart budget (%d in "
                        "%.0fs window) is exhausted",
                        wid, self.config.max_worker_restarts, window_s,
                    )

    def _spawn_agents(self) -> None:
        self._ensure_agents(
            self._bundle_node(i) for i in range(self.config.num_workers)
        )

    def _ensure_agents(self, node_ids) -> None:
        """One store agent per non-driver node that hosts workers (the
        per-node data-plane process; the driver node's agent is embedded in
        the master). Idempotent — called again when dynamic allocation
        lands workers on new nodes."""
        with self._lock:
            agent_nodes = (
                set(node_ids) - {DEFAULT_NODE} - set(self._agent_procs)
            )
        if not agent_nodes:
            return
        for node_id in sorted(agent_nodes):
            spec = LaunchSpec(
                argv=[
                    "-m",
                    "raydp_tpu.store.agent",
                    "--namespace",
                    self.namespace,
                    "--node-id",
                    node_id,
                    "--master",
                    self.master.address,
                    "--bind-host",
                    self.config.bind_host,
                ],
                node_id=node_id,
                log_path=os.path.join(self._log_dir, f"agent-{node_id}.log"),
                env=self._child_trace_env(),
                cwd=_repo_root(),
            )
            with self._lock:
                self._agent_procs[node_id] = self.launcher.launch(spec)
        with self._lock:
            all_agent_nodes = set(self._agent_procs)
        self.master.expect_agents(all_agent_nodes)
        if not self.master.wait_for_agents(60.0):
            raise ClusterError(
                f"store agents failed to register (logs: {self._log_dir})"
            )

    def _place_group(self) -> None:
        if self.config.placement_group is not None:
            self.pg = self.config.placement_group
            return
        if self.config.placement_strategy is None:
            self.pg = None
            return
        bundles = [
            {
                "cpu": float(self.config.cores_per_worker),
                "memory": float(self.config.memory_per_worker),
            }
            for _ in range(self.config.num_workers)
        ]
        self.pg = pl.place(
            bundles, self.config.placement_strategy, self.master.nodes
        )

    def _bundle_node(self, index: int) -> str:
        if self.pg is None:
            # No placement group: on a multi-node cluster, spread workers
            # round-robin over nodes so every host gets a data-plane
            # presence; single node degenerates to node-0.
            nodes = self.master.nodes if self.master is not None else []
            if len(nodes) > 1:
                return nodes[index % len(nodes)].node_id
            return DEFAULT_NODE
        indexes = self.config.placement_bundle_indexes
        if indexes is not None:
            index = indexes[index % len(indexes)]
        # Round-robin over bundles (reference: RayAppMaster.scala:281-289).
        bundle = self.pg.bundles[index % len(self.pg.bundles)]
        return bundle.node_id or "node-0"

    def _child_trace_env(self) -> Dict[str, str]:
        from raydp_tpu.telemetry import accounting as _acct
        from raydp_tpu.telemetry import propagation as _prop

        # Trace + job identity travel together: a child process joins
        # the driver's trace AND bills usage to the ambient job (empty
        # entries when there is nothing to propagate).
        return {
            **_prop.env_for_child(self._trace_ctx),
            **_acct.env_for_child(),
        }

    def _spawn_worker(self, node_id: Optional[str] = None) -> str:
        seq = next(self._worker_seq)
        worker_id = f"w{seq}"
        if node_id is None:
            node_id = self._bundle_node(seq)
        spec = LaunchSpec(
            argv=[
                "-m",
                "raydp_tpu.cluster.worker_main",
                "--worker-id",
                worker_id,
                "--master",
                self.master.address,
                "--node-id",
                node_id,
                "--cores",
                str(self.config.cores_per_worker),
                "--memory",
                str(self.config.memory_per_worker),
                "--bind-host",
                self.config.bind_host,
            ],
            node_id=node_id,
            log_path=os.path.join(self._log_dir, f"{worker_id}.log"),
            env={"JAX_PLATFORMS": "cpu", **self._child_trace_env()},
            cwd=_repo_root(),
        )
        proc = self.launcher.launch(spec)
        with self._lock:
            self._procs[worker_id] = proc
            self._worker_nodes[worker_id] = node_id
        from raydp_tpu.telemetry import events as _events

        _events.emit("worker/spawn", worker=worker_id, node=node_id)
        return worker_id

    def shutdown(self, del_obj_holder: bool = True, fast: bool = False) -> None:
        """Stop workers; tear down master now (del_obj_holder=True) or keep
        it + holder objects alive for later release_holder().

        ``fast=True`` (interpreter-exit path) skips the graceful RPC dance:
        thread pools are already being torn down by CPython at that point,
        so RPCs to/from the master would race executor shutdown.
        """
        self._elastic_stop.set()  # teardown must never trigger respawns
        from raydp_tpu.telemetry import flight_recorder as _flight

        _flight.record("state", "cluster_shutdown",
                       namespace=self.namespace, fast=fast)
        with self._lock:
            worker_ids = list(self._procs)
        if fast:
            # Workers die hard; agents are NOT terminated here — they must
            # stay reachable so release_holder() can broadcast DestroyStore
            # before stopping them (else remote-node segments leak).
            with self._lock:
                procs = list(self._procs.values())
                self._procs.clear()
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
        else:
            for worker_id in worker_ids:
                self._stop_worker(worker_id, kill_objects=False)
            self._flush_telemetry()
        self._pool.shutdown(wait=False)
        for attr in ("_slo_engine", "_ts_sampler"):
            plane = getattr(self, attr)
            if plane is not None:
                try:
                    plane.stop()
                except Exception:  # pragma: no cover - teardown best-effort
                    pass
                setattr(self, attr, None)
        if self._metrics_server is not None:
            try:
                self._metrics_server.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            self._metrics_server = None
        self._reset_trace_context()
        if self.master is not None:
            if del_obj_holder:
                self.release_holder()
        # Note: with del_obj_holder=False the store agents stay up — holder
        # objects on remote nodes must remain fetchable until
        # release_holder() (reference: stop_spark(del_obj_holder=False),
        # context.py:208-215).

    def _reset_trace_context(self) -> None:
        """Drop the job trace context — but only if it is still OURS:
        a driver may start a second cluster before fully tearing down
        the first, and that cluster's context must survive."""
        if self._trace_ctx is None:
            return
        from raydp_tpu.telemetry import propagation as _prop

        if _prop.process_context() == self._trace_ctx:
            _prop.set_process_context(None)
        self._trace_ctx = None

    def _flush_telemetry(self) -> None:
        """Persist lifecycle events + driver spans to JSONL on graceful
        shutdown (no-op unless RAYDP_TPU_TELEMETRY_DIR is set). Workers
        have already stopped, so their final WorkerStopped snapshots are
        merged into the master's telemetry view by now."""
        from raydp_tpu.telemetry import flush_spans, telemetry_dir, write_events

        if telemetry_dir() is None:
            return
        try:
            if self.master is not None:
                write_events(self.master.telemetry.events())
            flush_spans()
        except Exception:  # pragma: no cover - telemetry must not block exit
            logger.exception("telemetry flush failed")

    def release_holder(self) -> None:
        """Unlink holder-owned objects, stop agents + the master service."""
        if self.master is None:
            return
        self.master.release_holder()
        self.master.store.destroy()  # broadcasts DestroyStore to agents
        self._stop_agents()
        # Backstop for same-machine virtual nodes (and crashed agents):
        # sweep every segment of this namespace across ALL node prefixes.
        from raydp_tpu.store import shm

        for name in shm.list_segments(f"rdp-{self.namespace}-"):
            shm.unlink(name)
        self.master.shutdown()
        self.master = None

    def _stop_agents(self) -> None:
        with self._lock:
            procs = dict(self._agent_procs)
            self._agent_procs.clear()
        for node_id, proc in procs.items():
            agent = self.master.store.agent_for(node_id) if self.master else None
            if agent is not None:
                client = RpcClient(agent["address"], agent["service"])
                client.try_call("Stop", {}, timeout=2.0)
                client.close()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()

    def _stop_worker(self, worker_id: str, kill_objects: bool = True) -> None:
        # Pop the proc FIRST: once it is out of _procs the elastic loop
        # cannot mistake this intentional stop for a crash.
        with self._lock:
            proc = self._procs.pop(worker_id, None)
        client = self._client_for(worker_id)
        if client is not None:
            client.try_call("Stop", {}, timeout=2.0)
            client.close()
        with self._lock:
            self._worker_clients.pop(worker_id, None)
        if proc is not None:
            if client is None:
                # Never registered (no RPC path) — don't wait out a
                # heartbeat loop that won't stop; terminate directly.
                proc.terminate()
            try:
                proc.wait(timeout=10 if client is not None else 2)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        if kill_objects and self.master is not None:
            self.master.mark_worker_dead(worker_id, reason="killed")

    # -- dynamic allocation ---------------------------------------------
    def request_workers(self, num_additional: int) -> List[str]:
        """Grow the pool (dynamic allocation)."""
        current = len(self.alive_workers())
        self.master.expect_workers(current + num_additional)
        ids = [self._spawn_worker() for _ in range(num_additional)]
        # New workers may land on nodes the initial pool never used; those
        # nodes need a store agent before any object lands there.
        with self._lock:
            new_nodes = [self._worker_nodes[wid] for wid in ids]
        self._ensure_agents(new_nodes)
        if not self.master.wait_for_workers(60.0):
            raise ClusterError("additional workers failed to register")
        return ids

    def kill_worker(self, worker_id: str) -> None:
        """Shrink the pool; the worker's non-holder objects are unlinked,
        holder-owned objects survive (shuffle-survival semantics)."""
        self._stop_worker(worker_id, kill_objects=True)

    # -- object access ----------------------------------------------------
    @property
    def resolver(self):
        """Driver-side node-aware reader: local shm for driver-node objects,
        agent fetch for everything else."""
        if self._resolver is None:
            from raydp_tpu.store.resolver import ObjectResolver

            self._resolver = ObjectResolver(
                self.master.store, self.master.object_meta
            )
        return self._resolver

    # -- introspection ----------------------------------------------------
    def alive_workers(self) -> List[WorkerInfo]:
        return self.master.alive_workers() if self.master else []

    def cluster_resources(self) -> dict:
        return self.master.cluster_resources()

    def metrics_snapshot(self) -> dict:
        """Merged cluster-wide metrics: per-worker views (heartbeat-shipped
        deltas, tombstoned final snapshots for dead workers), a cross-worker
        aggregate, lifecycle events, and the driver's own registry."""
        if self.master is not None:
            return self.master.metrics_snapshot()
        from raydp_tpu.utils.profiling import metrics as _m

        return {
            "workers": {},
            "aggregate": {},
            "events": [],
            "driver": _m.snapshot(),
        }

    def prometheus_metrics(self) -> str:
        """The merged view as Prometheus text exposition v0.0.4."""
        from raydp_tpu.telemetry import render_prometheus

        return render_prometheus(self.metrics_snapshot())

    def trace_report(self) -> Optional[dict]:
        """Critical path + per-rank step skew over the job's merged
        trace (see :mod:`raydp_tpu.telemetry.analyze`). Flushes the
        driver's own spans first; worker spans arrive as workers flush
        (each heartbeat and on exit). None unless
        ``RAYDP_TPU_TELEMETRY_DIR`` is configured."""
        from raydp_tpu.telemetry import analyze, flush_spans, telemetry_dir

        directory = telemetry_dir()
        if directory is None:
            return None
        flush_spans()
        return analyze.trace_report(directory)

    def usage_report(self) -> dict:
        """Per-job usage totals folded from the merged cluster view:
        chip-seconds, host task-seconds, shuffle/staged/fetched bytes,
        HBM-byte-seconds, and compile-seconds, each billed to the
        :class:`~raydp_tpu.telemetry.accounting.JobContext` in scope
        when the work ran. The input the fair-share scheduler reads;
        also exported as the ``raydp_job_*`` Prometheus families."""
        from raydp_tpu.telemetry import accounting as _acct

        return _acct.usage_report(self.metrics_snapshot())

    def scheduler_report(self) -> dict:
        """Control-plane arbiter state (parity with
        :meth:`usage_report`): capacity, in-use slots, admission-queue
        contents in grant order, active leases, per-job lifecycle
        states, and queue-wait statistics. ``{"enabled": False, ...}``
        when arbitration is off (``RAYDP_TPU_SCHED_CAPACITY`` unset —
        the single-tenant default; see doc/scheduling.md)."""
        from raydp_tpu.control import get_arbiter

        return get_arbiter().report()

    def events_report(self, job: Optional[str] = None) -> dict:
        """The cluster event timeline + MTTR report (parity with
        :meth:`usage_report`); also served at ``/debug/events``."""
        from raydp_tpu.telemetry import events as _events
        from raydp_tpu.telemetry import telemetry_dir

        records = _events.load_event_records(telemetry_dir(), job=job)
        return {"events": records, "mttr": _events.mttr_report(records)}

    def dashboard_report(self) -> dict:
        """The unified flywheel dashboard: train/ETL/serve/control
        sections folded from the merged view, the SLO status table, the
        event timeline tail + MTTR episodes, and per-job usage — one
        document (see :mod:`raydp_tpu.telemetry.dashboard`). Also
        served at ``/debug/dashboard`` and, in client mode, over the
        ``DashboardReport`` RPC."""
        from raydp_tpu.telemetry import dashboard as _dash
        from raydp_tpu.telemetry import events as _events
        from raydp_tpu.telemetry import telemetry_dir

        records = _events.load_event_records(telemetry_dir())
        try:
            scheduler = self.scheduler_report()
        except Exception:
            scheduler = None
        return _dash.build(
            self.metrics_snapshot(), scheduler=scheduler, events=records
        )

    def health_report(self) -> Optional[dict]:
        """Aggregated cluster health (parity with :meth:`trace_report`):
        per-worker heartbeat age + watchdog stall flags shipped on
        heartbeats, stalled/dead/late worker lists, slowest-rank
        attribution, and the driver's own watchdog state. None before
        :meth:`start`."""
        if self.master is None:
            return None
        return self.master.health_report()

    def progress_report(self) -> dict:
        """Live stage progress — in-flight stages with done/total task
        counts, recently completed stages, and stage-store totals. Also
        served on ``/debug/progress`` of the driver's metrics endpoint."""
        if self.master is not None:
            return self.master.progress_report()
        from raydp_tpu.telemetry.progress import progress, stage_store

        report = progress.report()
        report["stage_totals"] = stage_store.snapshot()["totals"]
        return report

    def capture_profile(
        self, seconds: float = 3.0, out_dir: Optional[str] = None
    ) -> Optional[dict]:
        """Cluster-wide coordinated trace capture: every alive worker —
        and the driver itself — records a ``jax.profiler`` trace for
        ``seconds`` starting at (nearly) the same wall instant; the
        per-process archives are merged into one clock-aligned Perfetto
        file (``merged_trace.json`` under the returned ``out_dir``).

        Worker archives travel through the shm object store (a ref on
        the reply, resolved driver-side), so the trace zips ride the
        data plane, not the control RPC. Also exposed as
        ``/debug/profile?seconds=N`` on the driver metrics endpoint.
        None before :meth:`start`."""
        if self.master is None:
            return None
        from raydp_tpu.telemetry import device_profiler

        workers = self.alive_workers()
        payloads: Dict[str, dict] = {}
        errors: Dict[str, str] = {}

        def _one(worker_id: str) -> None:
            client = self._client_for(worker_id)
            if client is None:
                errors[worker_id] = "no client"
                return
            try:
                payloads[worker_id] = client.call(
                    "ProfileRequest", {"seconds": seconds},
                    timeout=seconds + 30.0,
                )
            except Exception as exc:
                errors[worker_id] = str(exc)

        threads = [
            threading.Thread(target=_one, args=(w.worker_id,), daemon=True)
            for w in workers
        ]
        for t in threads:
            t.start()
        # The driver participates too, concurrent with the fan-out: its
        # infeed/dispatch threads are half the step-phase story.
        driver_payload = device_profiler.capture_trace_archive(seconds)
        driver_payload["worker_id"] = "driver"
        for t in threads:
            t.join(timeout=seconds + 60.0)
        ordered = [driver_payload] + [
            payloads[wid] for wid in sorted(payloads)
        ]
        for payload in ordered:  # store-shipped archives → bytes
            ref = payload.pop("ref", None)
            if ref is not None and "zip" not in payload:
                payload["zip"] = self.resolver.get_bytes(ref)
        merged = device_profiler.merge_rank_traces(ordered, out_dir)
        if errors:
            merged["errors"] = errors
        return merged

    # -- task submission --------------------------------------------------
    def submit(
        self,
        fn: Callable,
        *args,
        worker_id: Optional[str] = None,
        timeout: float = 300.0,
        **kwargs,
    ) -> Any:
        """Run ``fn(worker_ctx, *args, **kwargs)`` on one worker."""
        return self.submit_async(
            fn, *args, worker_id=worker_id, timeout=timeout, **kwargs
        ).result()

    def submit_async(
        self,
        fn: Callable,
        *args,
        worker_id: Optional[str] = None,
        timeout: float = 300.0,
        retries: int = 2,
        data_args: Sequence = (),
        meta_sink: Optional[Callable] = None,
        **kwargs,
    ) -> Future:
        """Run ``fn(worker_ctx, *args, *data_args, **kwargs)`` on a worker.

        ``data_args`` (Arrow tables) move through the shm object store:
        the tables are written into the driver's store here and only
        their ObjectRefs are shipped in the RunTask envelope — a
        co-located worker maps them zero-copy, a remote one streams them
        from this node's agent in bounded chunks. The control-plane
        payload stays O(refs) regardless of table size.
        """
        staged = self._stage_data_args(data_args)
        payload = {
            "fn": cloudpickle.dumps(fn),
            "args": args,
            "kwargs": kwargs,
        }
        if staged:
            payload["data_refs"] = staged
        # The RunTask RPC fires from a pool thread; capture the
        # SUBMITTING thread's trace context here so the worker-side task
        # span parents under e.g. the driver's df/stage span instead of
        # the bare job root.
        from raydp_tpu.telemetry import accounting as _acct
        from raydp_tpu.telemetry import propagation as _prop

        trace_ctx = _prop.current_context()
        # Same capture for the job: the RunTask envelope must bill the
        # SUBMITTING thread's job, not whatever the pool thread holds.
        job_ctx = _acct.current_job()

        def run():
            import grpc

            preferred = worker_id
            last: Optional[BaseException] = None
            for attempt in range(retries + 1):
                try:
                    target = self._pick_worker(preferred)
                except ClusterError as exc:
                    # Preferred worker gone (or none alive yet — elastic
                    # respawn may still be bringing one back).
                    last = exc
                    preferred = None
                    time.sleep(0.3 * (attempt + 1))
                    continue
                client = self._client_for(target)
                if client is None:
                    preferred = None
                    last = ClusterError(f"worker {target} is gone")
                    continue
                try:
                    reply = client.call("RunTask", payload, timeout=timeout)
                    if meta_sink is not None:
                        try:
                            meta_sink(0, target, reply.get("exec_s", 0.0))
                        except Exception:
                            pass  # stats sink must never fail the task
                    return reply["result"]
                except grpc.RpcError as exc:
                    code = exc.code()
                    # Connectivity loss (UNAVAILABLE) or a server that shut
                    # down with our call in flight (CANCELLED — a worker
                    # exiting tears down its gRPC server and cancels open
                    # RPCs) both mean the worker is gone and the idempotent
                    # stage task is retriable elsewhere; a DEADLINE_EXCEEDED
                    # is a slow task on a healthy worker and must not
                    # unlink its objects or re-run the work.
                    # ...except when WE initiated the teardown: shutdown
                    # closes worker channels with calls possibly in
                    # flight, and those surface as CANCELLED too —
                    # re-running their tasks on surviving workers would
                    # duplicate side effects and stall the teardown.
                    if self._elastic_stop.is_set():
                        raise ClusterError(
                            f"task RPC to worker {target} failed: {code} "
                            "(cluster is shutting down)"
                        ) from exc
                    if (
                        code in (grpc.StatusCode.UNAVAILABLE,
                                 grpc.StatusCode.CANCELLED)
                        and self.master is not None
                    ):
                        self.master.mark_worker_dead(
                            target, reason="worker unreachable"
                        )
                        last = ClusterError(
                            f"task RPC to worker {target} failed: {code}"
                        )
                        preferred = None
                        continue  # idempotent stage task: retry elsewhere
                    raise ClusterError(
                        f"task RPC to worker {target} failed: {code}"
                    ) from exc
            raise ClusterError(
                f"task failed after {retries + 1} attempts: {last}"
            ) from last

        def traced_run():
            try:
                with _prop.propagated(trace_ctx), _acct.job_scope(job_ctx):
                    return run()
            finally:
                # Staged data_args are scratch: the worker has consumed
                # them (re-put under its own ownership where needed) by
                # the time the RPC returns. Unlink keeps driver shm flat.
                self._discard_staged(staged)

        return self._pool.submit(traced_run)

    def map_tasks(
        self,
        fn: Callable,
        items: List[Any],
        timeout: float = 300.0,
    ) -> List[Any]:
        """Run ``fn(ctx, item)`` for each item, load-balanced round-robin
        over alive workers; preserves order."""
        futures = [
            self.submit_async(fn, item, timeout=timeout) for item in items
        ]
        return [f.result() for f in futures]

    # -- batched submission (one envelope per worker) ---------------------
    def submit_batch(
        self,
        specs: Sequence[TaskSpec],
        timeout: float = 300.0,
        retries: int = 2,
        meta_sink: Optional[Callable] = None,
    ) -> List[Future]:
        """Run many tasks with ONE RunTaskBatch envelope per worker.

        Tasks are grouped by their (locality-preferred) target worker and
        each group ships as a single RPC carrying all of that worker's
        tasks — per-call gRPC + pickle overhead is paid once per worker
        instead of once per partition. Each distinct ``fn`` is serialized
        once per envelope. Returns one Future per spec, in order; a
        future resolves as soon as its worker's envelope lands, so
        callers can stream per-task completions (``add_done_callback``)
        instead of waiting for the slowest worker.

        Worker death fails only that worker's envelope; its tasks are
        reassigned to surviving workers (stage tasks are idempotent),
        up to ``retries`` rounds.

        ``meta_sink(spec_index, worker_id, exec_s)`` — optional per-task
        completion callback carrying the executing worker and its
        measured task seconds (stage-stats attribution); invoked before
        the matching future resolves.
        """
        futures: List[Future] = [Future() for _ in specs]
        if not specs:
            return futures
        from raydp_tpu.telemetry import accounting as _acct
        from raydp_tpu.telemetry import propagation as _prop

        trace_ctx = _prop.current_context()
        job_ctx = _acct.current_job()

        def orchestrate():
            with _prop.propagated(trace_ctx), _acct.job_scope(job_ctx):
                try:
                    self._run_batch(
                        list(specs), futures, timeout, retries, meta_sink
                    )
                except BaseException as exc:  # noqa: BLE001 - fan to futures
                    for f in futures:
                        if not f.done():
                            f.set_exception(exc)

        self._pool.submit(orchestrate)
        return futures

    def _run_batch(
        self,
        specs: List[TaskSpec],
        futures: List[Future],
        timeout: float,
        retries: int,
        meta_sink: Optional[Callable] = None,
    ) -> None:
        staged = [self._stage_data_args(s.data_args) for s in specs]
        try:
            pending = list(range(len(specs)))
            last: Optional[BaseException] = None
            for attempt in range(retries + 1):
                groups: Dict[str, List[int]] = {}
                try:
                    for i in pending:
                        target = self._resolve_batch_target(
                            specs[i], attempt
                        )
                        groups.setdefault(target, []).append(i)
                except ClusterError as exc:
                    # No alive workers (elastic respawn may still be
                    # bringing one back) — wait and retry the round.
                    last = exc
                    time.sleep(0.3 * (attempt + 1))
                    continue
                results: Dict[str, Any] = {}
                threads = []
                for wid, idxs in groups.items():
                    t = threading.Thread(
                        target=self._call_batch_into,
                        args=(results, wid, idxs, specs, staged, timeout,
                              futures, meta_sink),
                        name=f"raydp-batch-{wid}",
                        daemon=True,
                    )
                    t.start()
                    threads.append(t)
                # Futures resolve INSIDE each envelope thread the moment
                # its worker replies (per-envelope streaming); this join
                # only gates the retry round on the stragglers.
                for t in threads:
                    t.join()
                next_pending: List[int] = []
                for wid, idxs in groups.items():
                    outcome = results.get(wid)
                    if outcome is _BATCH_RESOLVED:
                        continue
                    if isinstance(outcome, _WorkerGone):
                        last = ClusterError(str(outcome))
                        next_pending.extend(idxs)
                        continue
                    if isinstance(outcome, BaseException):
                        raise outcome
                    raise ClusterError(
                        f"batch envelope to {wid} vanished without an "
                        f"outcome"
                    )
                pending = next_pending
                if not pending:
                    return
            for i in pending:
                if not futures[i].done():
                    futures[i].set_exception(
                        ClusterError(
                            f"batched task failed after {retries + 1} "
                            f"attempts: {last}"
                        )
                    )
        finally:
            for refs in staged:
                self._discard_staged(refs)

    def _resolve_batch_target(self, spec: TaskSpec, attempt: int) -> str:
        """Placement for one batched task: the preferred worker on the
        first attempt, then any alive worker on the spec's hint node
        (``node_id`` — keeps shuffle merges next to their bytes when the
        chosen worker died), then plain round-robin. Raises ClusterError
        when nothing is alive."""
        if attempt == 0 and spec.worker_id is not None:
            try:
                return self._pick_worker(spec.worker_id)
            except ClusterError:
                pass  # preferred worker gone; fall through to the node
        if spec.node_id is not None:
            node_workers = sorted(
                w.worker_id
                for w in self.alive_workers()
                if w.node_id == spec.node_id
            )
            if node_workers:
                return node_workers[next(self._rr) % len(node_workers)]
        return self._pick_worker(None)

    def _call_batch_into(
        self,
        results: Dict[str, Any],
        worker_id: str,
        idxs: List[int],
        specs: List[TaskSpec],
        staged: List[List[Any]],
        timeout: float,
        futures: Optional[List[Future]] = None,
        meta_sink: Optional[Callable] = None,
    ) -> None:
        """One RunTaskBatch envelope to one worker. On success the
        envelope's futures resolve HERE, the moment this worker replies
        — not after every worker's thread is joined — so downstream
        completion callbacks (streaming stages, ingest) fire while
        slower envelopes are still running. ``results`` then carries the
        resolved sentinel; failures (_WorkerGone / hard error) still
        land there for the retry loop."""
        import grpc

        try:
            client = self._client_for(worker_id)
            if client is None:
                raise _WorkerGone(f"worker {worker_id} is gone")
            fn_blobs: List[bytes] = []
            fn_index: Dict[int, int] = {}  # id(fn) -> slot, dedup per envelope
            tasks = []
            for i in idxs:
                spec = specs[i]
                slot = fn_index.get(id(spec.fn))
                if slot is None:
                    slot = len(fn_blobs)
                    fn_blobs.append(cloudpickle.dumps(spec.fn))
                    fn_index[id(spec.fn)] = slot
                task = {"fn": slot, "args": spec.args, "kwargs": spec.kwargs}
                if staged[i]:
                    task["data_refs"] = staged[i]
                tasks.append(task)
            payload = {"fns": fn_blobs, "tasks": tasks}
            try:
                reply = client.call("RunTaskBatch", payload, timeout=timeout)
            except grpc.RpcError as exc:
                code = exc.code()
                if self._elastic_stop.is_set():
                    raise ClusterError(
                        f"batch RPC to worker {worker_id} failed: {code} "
                        "(cluster is shutting down)"
                    ) from exc
                # Same death taxonomy as submit_async: UNAVAILABLE /
                # CANCELLED mean the worker is gone and the idempotent
                # stage tasks may re-run elsewhere; anything else is a
                # hard error.
                if (
                    code in (grpc.StatusCode.UNAVAILABLE,
                             grpc.StatusCode.CANCELLED)
                    and self.master is not None
                ):
                    self.master.mark_worker_dead(
                        worker_id, reason="worker unreachable"
                    )
                    raise _WorkerGone(
                        f"batch RPC to worker {worker_id} failed: {code}"
                    ) from exc
                raise ClusterError(
                    f"batch RPC to worker {worker_id} failed: {code}"
                ) from exc
            res_list = reply["results"]
            if futures is None:
                results[worker_id] = res_list
                return
            for i, res in zip(idxs, res_list):
                if res.get("ok"):
                    if meta_sink is not None:
                        try:
                            meta_sink(i, worker_id, res.get("exec_s", 0.0))
                        except Exception:
                            pass  # sink must never fail the batch
                    futures[i].set_result(res.get("value"))
                else:
                    futures[i].set_exception(
                        RpcError(
                            f"batched task failed on {worker_id}: "
                            f"{res.get('error')}\n"
                            f"{res.get('traceback', '')}"
                        )
                    )
            results[worker_id] = _BATCH_RESOLVED
        except BaseException as exc:  # noqa: BLE001 - marshalled to caller
            results[worker_id] = exc

    # -- data-plane staging ----------------------------------------------
    def _stage_data_args(self, tables: Sequence) -> List[Any]:
        """Write Arrow tables into the driver-node store; only the refs
        ride the control plane."""
        if not tables:
            return []
        from raydp_tpu.telemetry import accounting as _acct

        store = self.master.store
        refs = [store.put_arrow_table(t) for t in tables]
        _acct.add_usage(
            _acct.STAGED_BYTES, sum(r.size for r in refs)
        )
        return refs

    def _discard_staged(self, refs: Sequence) -> None:
        if not refs or self.master is None:
            return
        for ref in refs:
            try:
                self.master.store.delete(ref)
            except Exception:  # pragma: no cover - scratch cleanup
                pass

    def _pick_worker(self, worker_id: Optional[str]) -> str:
        workers = self.alive_workers()
        if not workers:
            raise ClusterError("no alive workers")
        if worker_id is not None:
            if not any(w.worker_id == worker_id for w in workers):
                raise ClusterError(f"worker {worker_id} not alive")
            return worker_id
        return workers[next(self._rr) % len(workers)].worker_id

    def _client_for(self, worker_id: str) -> Optional[RpcClient]:
        with self._lock:
            client = self._worker_clients.get(worker_id)
            if client is not None:
                return client
        info = next(
            (w for w in self.alive_workers() if w.worker_id == worker_id), None
        )
        if info is None:
            return None
        client = RpcClient(info.address, "raydp.Worker")
        with self._lock:
            winner = self._worker_clients.setdefault(worker_id, client)
        if winner is not client:  # lost a create race; drop our channel
            client.close()
        return winner


def _slug(name: str) -> str:
    return "".join(c if c.isalnum() or c == "-" else "-" for c in name.lower())


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
