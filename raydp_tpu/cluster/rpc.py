"""Minimal gRPC layer: named bytes→bytes methods, pickle payloads.

One control-plane transport replacing the reference's four (Spark RPC,
Ray actor calls, py4j, gRPC — reference: SURVEY §2.4). Built on grpc's
generic method handlers so no protoc codegen is needed (grpcio-tools is
not in this image); messages are Python dicts pickled with cloudpickle
(which also lets task payloads carry closures, the reference's MPI
function-shipping pattern — reference: python/raydp/mpi/mpi_job.py:321-335).

Trace propagation rides the envelope: the client stamps the caller's
trace context into the request dict as ``traceparent``
(:func:`raydp_tpu.telemetry.propagation.inject`) and the server runs
each handler inside ``propagated(ctx)``, so spans recorded on handler
threads parent under the caller's span. The key is left in the request
— handlers that defer work to another thread (the SPMD runner queue)
forward it themselves. Job attribution rides the same way: a ``job``
entry (:mod:`raydp_tpu.telemetry.accounting`) is injected next to the
traceparent and the handler runs inside ``job_scope``, so usage a
worker emits on a caller's behalf bills to the caller's job.

The health plane rides here too: every client call is bracketed as an
in-flight ``rpc`` op (a peer that never answers shows up in the
watchdog's stall report with the method name), and sends/recvs land in
the flight-recorder ring so a postmortem bundle shows the last
control-plane traffic before death.
"""
from __future__ import annotations

import contextlib
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import cloudpickle
import grpc

from raydp_tpu import fault as _fault
from raydp_tpu.telemetry import accounting as _acct
from raydp_tpu.telemetry import flight_recorder as _flight
from raydp_tpu.telemetry import propagation as _prop
from raydp_tpu.telemetry import watchdog as _watchdog
from raydp_tpu.utils.profiling import metrics as _metrics


def _identity(b: bytes) -> bytes:
    return b


# Handler methods that run user code (or, for ProfileRequest, sleep for
# the requested capture window) and so legitimately outlive the default
# stall threshold; everything else is control-plane and fast.
_LONG_HANDLER_METHODS = frozenset(
    {"RunTask", "RunTaskBatch", "RunFunction", "ProfileRequest",
     "ExecuteBatch"}
)


class RpcError(RuntimeError):
    """Remote handler raised; message carries the remote traceback."""


class FaultInjectedRpcError(grpc.RpcError):
    """An ``rpc_drop`` fault-plan clause dropped this call.

    Subclasses ``grpc.RpcError`` so every existing transport-error
    path (``try_call``, heartbeat miss accounting, client retries)
    treats an injected drop exactly like a real UNAVAILABLE peer.
    """

    def __init__(self, method: str):
        super().__init__(f"fault plan dropped rpc {method}")
        self._method = method

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return f"fault plan dropped rpc {self._method}"


class RpcServer:
    """Hosts a service: a dict of ``{method_name: fn(dict) -> dict}``."""

    def __init__(
        self,
        service_name: str,
        handlers: Dict[str, Callable[[dict], dict]],
        host: str = "127.0.0.1",
        port: int = 0,
        max_workers: int = 16,
        advertise_host: Optional[str] = None,
    ):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 512 * 1024 * 1024),
                ("grpc.max_receive_message_length", 512 * 1024 * 1024),
            ],
        )
        rpc_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                self._wrap(f"{service_name}.{name}", fn),
                request_deserializer=_identity,
                response_serializer=_identity,
            )
            for name, fn in handlers.items()
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(service_name, rpc_handlers),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RuntimeError(f"failed to bind {host}:{port}")
        # Advertised (routable) address may differ from the bind address:
        # binding 0.0.0.0 accepts cross-host connections but peers must dial
        # a concrete IP (reference: SPMD workers advertise local_ip; the
        # reference binds Spark RPC on the driver host option,
        # ray_cluster.py:65-67).
        if advertise_host:
            self.host = advertise_host
        elif host in ("0.0.0.0", "::", ""):
            from raydp_tpu.utils.net import local_ip

            self.host = local_ip()
        else:
            self.host = host
        self._server.start()

    @staticmethod
    def _wrap(method: str, fn: Callable[[dict], dict]):
        def handler(request_bytes: bytes, context) -> bytes:
            t0 = time.monotonic()
            try:
                request = cloudpickle.loads(request_bytes)
                ctx = _prop.extract(request)
                scope = (
                    _prop.propagated(ctx)
                    if ctx is not None
                    else contextlib.nullcontext()
                )
                # Job attribution rides the same envelope: usage the
                # handler emits (task seconds, bytes) bills to the
                # caller's job, not the worker's own identity.
                jctx = _acct.extract(request)
                job_scope = (
                    _acct.job_scope(jctx)
                    if jctx is not None
                    else contextlib.nullcontext()
                )
                # A deadlocked handler is attributed by the watchdog as
                # "rpc/handler" with the method name. Methods that run
                # user code (a whole task body / shipped function) are
                # expected to take long — their threshold is raised so a
                # healthy 5-minute task is not reported as a wedge;
                # control-plane handlers keep the sharp default.
                stall_s = (
                    _watchdog.long_stall_s()
                    if method in _LONG_HANDLER_METHODS else None
                )
                with scope, job_scope, _watchdog.inflight(
                    "rpc/handler", method=method, stall_after_s=stall_s
                ):
                    reply = fn(request)
                _flight.record(
                    "rpc", method, dir="recv",
                    duration_s=round(time.monotonic() - t0, 6),
                )
                return cloudpickle.dumps({"ok": True, "value": reply})
            except Exception as exc:  # ship the error to the caller
                import traceback

                _flight.record(
                    "rpc", method, dir="recv", status="error",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
                return cloudpickle.dumps(
                    {
                        "ok": False,
                        "error": f"{type(exc).__name__}: {exc}",
                        "traceback": traceback.format_exc(),
                    }
                )

        return handler

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)


class RpcClient:
    """Calls methods on an RpcServer: ``client.call("Method", {...})``."""

    def __init__(self, address: str, service_name: str, timeout: float = 30.0):
        self.address = address
        self._service = service_name
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_send_message_length", 512 * 1024 * 1024),
                ("grpc.max_receive_message_length", 512 * 1024 * 1024),
            ],
        )
        self._stubs: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def call(self, method: str, request: Optional[dict] = None, timeout=None):
        with self._lock:
            stub = self._stubs.get(method)
            if stub is None:
                stub = self._channel.unary_unary(
                    f"/{self._service}/{method}",
                    request_serializer=_identity,
                    response_deserializer=_identity,
                )
                self._stubs[method] = stub
        qualified = f"{self._service}.{method}"
        t0 = time.monotonic()
        # The op can legitimately stay in flight until the RPC deadline
        # (grpc fails it then, ending the bracket) — so the stall
        # threshold follows the deadline instead of crying wolf at the
        # default 60s. Deadline-less stubs (SPMD control channels) fall
        # back to the long-op threshold.
        eff_timeout = timeout if timeout is not None else self._timeout
        token = _watchdog.tracker.begin(
            "rpc", method=qualified, peer=self.address,
            stall_after_s=(
                eff_timeout if eff_timeout is not None
                else _watchdog.long_stall_s()
            ),
        )
        request_bytes = cloudpickle.dumps(
            _acct.inject(_prop.inject(request or {}))
        )
        # Control-plane envelope size. Data is supposed to move through
        # the shm object store, so a fat counter here means some path is
        # smuggling table bytes through RPC (exported as
        # raydp_rpc_payload_bytes; asserted small in tests).
        _metrics.counter_add("rpc/payload_bytes", len(request_bytes))
        try:
            # Fault-plan hook: an rpc_delay clause sleeps here (inside the
            # watchdog bracket, so a big injected delay is attributed to
            # this call); an rpc_drop clause turns the send into a
            # synthetic UNAVAILABLE before any bytes hit the wire.
            if _fault.active() and _fault.on_rpc(qualified) == "drop":
                raise FaultInjectedRpcError(qualified)
            reply_bytes = stub(request_bytes, timeout=eff_timeout)
        except Exception as exc:
            _flight.record(
                "rpc", qualified, dir="send", peer=self.address,
                status="transport-error",
                error=f"{type(exc).__name__}"[:200],
            )
            raise
        finally:
            _watchdog.tracker.end(token)
        reply = cloudpickle.loads(reply_bytes)
        _flight.record(
            "rpc", qualified, dir="send", peer=self.address,
            duration_s=round(time.monotonic() - t0, 6),
            **({} if reply.get("ok") else {"status": "remote-error"}),
        )
        if not reply.get("ok"):
            raise RpcError(
                f"remote {self._service}.{method} failed: "
                f"{reply.get('error')}\n{reply.get('traceback', '')}"
            )
        return reply.get("value")

    def try_call(self, method: str, request: Optional[dict] = None, timeout=None):
        """Like call() but returns None on transport errors (peer gone)."""
        try:
            return self.call(method, request, timeout)
        except (grpc.RpcError, RpcError):
            return None

    def wait_ready(self, timeout: float = 10.0) -> bool:
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            return True
        except grpc.FutureTimeoutError:
            return False

    def close(self) -> None:
        self._channel.close()
