from raydp_tpu.cluster.cluster import Cluster, ClusterError
from raydp_tpu.cluster.placement import (
    NodeInfo,
    PlacementError,
    PlacementGroup,
    detect_nodes,
    place,
)

__all__ = [
    "Cluster",
    "ClusterError",
    "NodeInfo",
    "PlacementError",
    "PlacementGroup",
    "detect_nodes",
    "place",
]
