"""Placement groups: bundle reservation over cluster nodes.

Capability parity with Ray placement groups as used by the reference
(reference: python/raydp/context.py:94-110 builds the group;
core/.../RayAppMaster.scala:281-289 round-robins executors over bundle
indexes). Strategies:

  * PACK         — prefer few nodes, best-effort
  * STRICT_PACK  — all bundles on one node, else error
  * SPREAD       — prefer distinct nodes, best-effort round-robin
  * STRICT_SPREAD— all bundles on distinct nodes, else error

Nodes are TPU-VM hosts; on a single machine, tests exercise multi-node
logic via virtual nodes (``RAYDP_TPU_VIRTUAL_NODES``).
"""
from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from raydp_tpu.utils.net import local_ip


@dataclass
class NodeInfo:
    node_id: str
    address: str
    resources: Dict[str, float]  # {"cpu": n, "memory": bytes, ...}

    def copy(self) -> "NodeInfo":
        return NodeInfo(self.node_id, self.address, dict(self.resources))


def detect_nodes(num_virtual: Optional[int] = None) -> List[NodeInfo]:
    """Discover cluster nodes. Single-host: one node with psutil resources,
    or N equal virtual nodes when requested (``num_virtual`` argument or
    RAYDP_TPU_VIRTUAL_NODES env — tests and local multi-node simulation;
    the reference similarly simulates multi-node with multiple JVMs on one
    host, SURVEY §4)."""
    import psutil

    # Logical-resource override, like `ray start --num-cpus N` (the
    # reference CI boots its head node that way, raydp.yml:103-106).
    cpus = float(
        os.environ.get("RAYDP_TPU_NUM_CPUS") or (psutil.cpu_count() or 1)
    )
    mem = float(psutil.virtual_memory().total)
    n_virtual = (
        num_virtual
        if num_virtual is not None
        else int(os.environ.get("RAYDP_TPU_VIRTUAL_NODES", "0"))
    )
    ip = local_ip()
    if n_virtual <= 1:
        return [NodeInfo("node-0", ip, {"cpu": cpus, "memory": mem})]
    return [
        NodeInfo(
            f"node-{i}",
            ip,
            {"cpu": cpus / n_virtual, "memory": mem / n_virtual},
        )
        for i in range(n_virtual)
    ]


@dataclass
class Bundle:
    """One resource reservation; placed on exactly one node."""

    resources: Dict[str, float]
    node_id: Optional[str] = None  # assigned at placement time


class PlacementError(RuntimeError):
    pass


@dataclass
class PlacementGroup:
    bundles: List[Bundle]
    strategy: str
    group_id: str = field(
        default_factory=lambda: f"pg-{next(_pg_counter)}"
    )

    @property
    def bundle_node_ids(self) -> List[Optional[str]]:
        return [b.node_id for b in self.bundles]


_pg_counter = itertools.count()


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in need.items())


def _reserve(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def place(
    bundles: List[Dict[str, float]],
    strategy: str,
    nodes: List[NodeInfo],
) -> PlacementGroup:
    """Assign each bundle a node per the strategy, or raise PlacementError."""
    if not bundles:
        raise PlacementError("placement group needs at least one bundle")
    group = PlacementGroup([Bundle(dict(b)) for b in bundles], strategy)
    avail = {n.node_id: dict(n.resources) for n in nodes}
    order = [n.node_id for n in nodes]

    if strategy in ("PACK", "STRICT_PACK"):
        # Find one node that holds all bundles.
        for node_id in order:
            trial = dict(avail[node_id])
            ok = True
            for b in group.bundles:
                if _fits(trial, b.resources):
                    _reserve(trial, b.resources)
                else:
                    ok = False
                    break
            if ok:
                for b in group.bundles:
                    b.node_id = node_id
                return group
        if strategy == "STRICT_PACK":
            raise PlacementError(
                f"STRICT_PACK: no single node fits {len(group.bundles)} bundles"
            )
        # PACK fallback: greedy first-fit across nodes.
        return _first_fit(group, avail, order)

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        used_nodes = set()
        for b in group.bundles:
            chosen = None
            for node_id in order:
                if node_id in used_nodes:
                    continue
                if _fits(avail[node_id], b.resources):
                    chosen = node_id
                    break
            if chosen is None:
                if strategy == "STRICT_SPREAD":
                    raise PlacementError(
                        "STRICT_SPREAD: not enough distinct nodes "
                        f"({len(nodes)} nodes, {len(group.bundles)} bundles)"
                    )
                # SPREAD best-effort: reuse the least-loaded fitting node
                # (most remaining cpu) so overflow stays balanced.
                fitting = [
                    node_id for node_id in order
                    if _fits(avail[node_id], b.resources)
                ]
                if not fitting:
                    raise PlacementError("SPREAD: no node fits bundle")
                chosen = max(fitting, key=lambda nid: avail[nid].get("cpu", 0.0))
            _reserve(avail[chosen], b.resources)
            used_nodes.add(chosen)
            b.node_id = chosen
        return group

    raise PlacementError(f"unknown strategy {strategy!r}")


def _first_fit(
    group: PlacementGroup, avail: Dict[str, Dict[str, float]], order: List[str]
) -> PlacementGroup:
    for b in group.bundles:
        for node_id in order:
            if _fits(avail[node_id], b.resources):
                _reserve(avail[node_id], b.resources)
                b.node_id = node_id
                break
        else:
            raise PlacementError("PACK: no node fits bundle")
    return group
