"""AppMaster: the control-plane service.

Role parity with the reference's RayAppMaster
(reference: core/.../deploy/raydp/RayAppMaster.scala:40-296): registers the
application, tracks workers (register / started / request / kill),
schedules workers onto placement-group bundles round-robin
(``RayAppMaster.scala:281-289``), detects worker death and cleans up, and —
new here — hosts the **object directory** with holder ownership (the
reference splits this into ObjectRefHolder + a Python holder actor).

Runs as a gRPC service in a thread of the driver process (default) so
holder-owned objects survive worker teardown for the driver's lifetime;
the service boundary means workers and remote drivers speak to it the
same way a detached deployment would.

Tracing: handlers run inside the caller's propagated trace context
(``RpcServer._wrap`` installs the request's ``traceparent``), so the
lifecycle events recorded here — ``cluster/worker_registered``,
``cluster/worker_stopped`` — attach to the job trace of the worker that
called in. ``cluster/worker_dead`` fires from the monitor thread, which
carries no request context and parents under the driver's process-level
job context instead.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from raydp_tpu.cluster import placement as pl
from raydp_tpu.cluster.rpc import RpcServer
from raydp_tpu.store.agent import agent_handlers
from raydp_tpu.telemetry import ClusterTelemetry
from raydp_tpu.telemetry import spans as _spans
from raydp_tpu.store.directory import DirectoryStore
from raydp_tpu.store.object_store import DEFAULT_NODE, OWNER_HOLDER, ObjectRef

logger = logging.getLogger(__name__)

SERVICE = "raydp.AppMaster"
# Generous by design: local crashes are detected instantly via the
# cluster's proc.poll() monitor, so the heartbeat path only covers hung
# or remote workers — and a CPU-saturated host (big shuffle on few
# cores) must not read as death.
HEARTBEAT_TIMEOUT_S = float(
    __import__("os").environ.get("RAYDP_TPU_HEARTBEAT_TIMEOUT", "45")
)


@dataclass
class WorkerInfo:
    worker_id: str
    address: str  # worker RPC endpoint
    pid: int
    node_id: str
    resources: Dict[str, float]
    state: str = "ALIVE"  # ALIVE | DEAD | STOPPED
    last_heartbeat: float = field(default_factory=time.monotonic)
    # Watchdog stall flags shipped on the last heartbeat (empty =
    # healthy): {component: {age_s, since_wall, count, attrs}}.
    stalls: Dict[str, dict] = field(default_factory=dict)


class AppMaster:
    """Control-plane state machine + its gRPC server."""

    def __init__(
        self,
        namespace: str,
        nodes: Optional[List[pl.NodeInfo]] = None,
        bind_host: str = "127.0.0.1",
        advertise_host: Optional[str] = None,
        port: int = 0,
    ):
        self.namespace = namespace
        self.nodes = nodes if nodes is not None else pl.detect_nodes()
        self.node_id = DEFAULT_NODE  # the master lives on the driver node
        self.store = DirectoryStore(namespace=namespace, node_id=self.node_id)
        self._workers: Dict[str, WorkerInfo] = {}
        self._lock = threading.RLock()
        self._registration_event = threading.Event()
        self._expected_workers = 0
        self._agent_event = threading.Event()
        self._expected_agent_nodes: set = set()
        self._monitor_stop = threading.Event()
        # Cluster-wide metrics view: workers ship registry deltas on
        # their heartbeats; this merges them keyed by worker id.
        self.telemetry = ClusterTelemetry()
        handlers = {
            "RegisterWorker": self._on_register_worker,
            "Heartbeat": self._on_heartbeat,
            "WorkerStopped": self._on_worker_stopped,
            "RegisterObject": self._on_register_object,
            "PutObject": self._on_put_object,
            "RegisterAgent": self._on_register_agent,
            "TransferToHolder": self._on_transfer_to_holder,
            "GetObjectMeta": self._on_get_object_meta,
            "ListObjects": self._on_list_objects,
            "DeleteObject": self._on_delete_object,
            "ListWorkers": self._on_list_workers,
            "ClusterResources": self._on_cluster_resources,
            "MetricsSnapshot": self._on_metrics_snapshot,
            "HealthReport": self._on_health_report,
            "ProgressReport": self._on_progress_report,
            "SchedulerReport": self._on_scheduler_report,
            "UsageReport": self._on_usage_report,
            "EventsReport": self._on_events_report,
            "DashboardReport": self._on_dashboard_report,
            "Ping": lambda req: {"pong": True, "namespace": self.namespace},
        }
        # The master doubles as the driver node's store agent (no extra
        # process on the node the driver already occupies).
        handlers.update(agent_handlers(self.store))
        self._server = RpcServer(
            SERVICE, handlers, host=bind_host, port=port,
            advertise_host=advertise_host,
        )
        self.store.register_agent(self.node_id, self._server.address, SERVICE)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="raydp-master-monitor", daemon=True
        )
        self._monitor.start()

    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        return self._server.address

    def expect_workers(self, n: int) -> None:
        with self._lock:
            self._expected_workers = n
            self._registration_event.clear()
            self._check_registration_barrier()

    def wait_for_workers(self, timeout: float = 60.0) -> bool:
        """Registration barrier (reference:
        RayCoarseGrainedSchedulerBackend.scala:155,180-182)."""
        return self._registration_event.wait(timeout)

    def expect_agents(self, node_ids) -> None:
        with self._lock:
            self._expected_agent_nodes = set(node_ids)
            self._agent_event.clear()
            self._check_agent_barrier()

    def wait_for_agents(self, timeout: float = 60.0) -> bool:
        return self._agent_event.wait(timeout)

    def _check_agent_barrier(self) -> None:
        if self._expected_agent_nodes <= set(self.store.agents()):
            self._agent_event.set()

    def object_meta(self, object_id: str):
        """In-process resolver hook: (ref, agent) for the driver."""
        return self.store.meta(object_id)

    def alive_workers(self) -> List[WorkerInfo]:
        with self._lock:
            return [w for w in self._workers.values() if w.state == "ALIVE"]

    def mark_worker_dead(self, worker_id: str, reason: str = "") -> None:
        """Worker-disconnect path (reference: RayAppMaster.scala:184-186
        kills executors on RPC disconnect). Unlinks the worker's
        non-transferred objects."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or info.state != "ALIVE":
                return
            info.state = "DEAD"
        # Tombstone, don't drop: the final shipped snapshot is exactly
        # what a straggler post-mortem needs.
        self.telemetry.tombstone(worker_id)
        self.telemetry.event("worker/dead", worker_id=worker_id,
                             reason=reason)
        _spans.event("cluster/worker_dead", worker_id=worker_id,
                     reason=reason)
        doomed = self.store.on_owner_died(worker_id)
        logger.warning(
            "worker %s dead (%s); unlinked %d objects",
            worker_id,
            reason,
            len(doomed),
        )

    def release_holder(self) -> int:
        """Unlink holder-owned objects (the del_obj_holder=True path)."""
        doomed = self.store.on_owner_died(OWNER_HOLDER)
        return len(doomed)

    def shutdown(self) -> None:
        self._monitor_stop.set()
        self._server.stop()

    # -- handlers -------------------------------------------------------
    def _on_register_worker(self, req: dict) -> dict:
        info = WorkerInfo(
            worker_id=req["worker_id"],
            address=req["address"],
            pid=req["pid"],
            node_id=req.get("node_id", "node-0"),
            resources=req.get("resources", {}),
        )
        with self._lock:
            self._workers[info.worker_id] = info
            self._check_registration_barrier()
        self.telemetry.event("worker/registered", worker_id=info.worker_id,
                             node_id=info.node_id, pid=info.pid)
        _spans.event("cluster/worker_registered", worker_id=info.worker_id,
                     node_id=info.node_id)
        logger.info("registered worker %s @ %s", info.worker_id, info.address)
        return {"namespace": self.namespace}

    def _check_registration_barrier(self) -> None:
        alive = sum(1 for w in self._workers.values() if w.state == "ALIVE")
        if self._expected_workers and alive >= self._expected_workers:
            self._registration_event.set()

    def _on_heartbeat(self, req: dict) -> dict:
        # Piggybacked metrics delta — merged even for workers this
        # master has written off (their last beats still carry data),
        # and outside the worker-table lock (telemetry has its own).
        delta = req.get("metrics")
        if delta:
            self.telemetry.apply(req["worker_id"], delta)
        with self._lock:
            info = self._workers.get(req["worker_id"])
            if info is None:
                return {"known": False}
            info.last_heartbeat = time.monotonic()
            # Unconditional assignment: a beat without a health payload
            # means the worker's watchdog sees no stall — recovery
            # clears the flag without a dedicated RPC.
            info.stalls = (req.get("health") or {}).get("stalls") or {}
            return {"known": info.state == "ALIVE"}

    def _on_worker_stopped(self, req: dict) -> dict:
        worker_id = req["worker_id"]
        # Graceful exit ships the FULL final snapshot; merge + tombstone
        # so the worker's lifetime totals outlive it.
        self.telemetry.apply(worker_id, req.get("metrics"), final=True)
        self.telemetry.event("worker/stopped", worker_id=worker_id)
        _spans.event("cluster/worker_stopped", worker_id=worker_id)
        with self._lock:
            info = self._workers.get(worker_id)
            if info is not None:
                info.state = "STOPPED"
        # Graceful stop loses non-transferred objects too — data survives
        # worker teardown only via the holder (reference semantics:
        # test_data_owner_transfer.py:34-78, stop_spark → OwnerDiedError).
        doomed = self.store.on_owner_died(worker_id)
        if doomed:
            logger.info(
                "worker %s stopped; unlinked %d non-transferred objects",
                worker_id,
                len(doomed),
            )
        return {}

    def _on_register_object(self, req: dict) -> dict:
        ref = req["ref"]
        # A worker this master already wrote off (disowned mid-task but
        # still finishing — the heartbeat-starvation survival path) may
        # register worker-owned objects whose segments were unlinked the
        # moment it was marked dead. Registering such a ref would hand
        # later readers a pointer to deleted storage; fail the task
        # loudly here instead (holder-owned refs — every DataFrame stage
        # output — are unaffected: the holder never "dies").
        owner = getattr(ref, "owner", None)
        if owner is not None and owner != OWNER_HOLDER:
            # check + register under ONE lock hold: mark_worker_dead
            # flips state to DEAD under this lock and only unlinks
            # afterwards, so with the lock held across both steps a
            # registration lands either strictly before the DEAD
            # transition (the subsequent on_owner_died unlinks it) or
            # after (this raises) — never in between as a dangling ref.
            with self._lock:
                info = self._workers.get(owner)
                if info is not None and info.state != "ALIVE":
                    raise RuntimeError(
                        f"owner {owner} was marked dead; its objects were "
                        "unlinked — refusing to register a dangling ref"
                    )
                self.store.register_ref(ref)
            return {}
        self.store.register_ref(ref)
        return {}

    def _on_put_object(self, req: dict) -> dict:
        """Remote-driver write path (client mode): bytes land in the
        driver node's store under the requested owner."""
        ref = self.store.put(
            req["data"],
            owner=req.get("owner", OWNER_HOLDER),
            num_rows=req.get("num_rows", -1),
        )
        return {"ref": ref}

    def _on_register_agent(self, req: dict) -> dict:
        self.store.register_agent(
            req["node_id"], req["address"], req["service"]
        )
        with self._lock:
            self._check_agent_barrier()
        return {"namespace": self.namespace}

    def _on_transfer_to_holder(self, req: dict) -> dict:
        return {"ref": self.store.transfer_to_holder(req["ref"])}

    def _on_get_object_meta(self, req: dict) -> dict:
        ref, agent = self.store.meta(req["object_id"])
        return {"ref": ref, "agent": agent}

    def _on_list_objects(self, req: dict) -> dict:
        return {"refs": self.store.refs()}

    def _on_delete_object(self, req: dict) -> dict:
        return {"deleted": self.store.delete(req["object_id"])}

    def _on_list_workers(self, req: dict) -> dict:
        with self._lock:
            return {"workers": list(self._workers.values())}

    def _on_cluster_resources(self, req: dict) -> dict:
        return self.cluster_resources()

    def _on_metrics_snapshot(self, req: dict) -> dict:
        return {"snapshot": self.metrics_snapshot()}

    def _on_health_report(self, req: dict) -> dict:
        return {"report": self.health_report()}

    def _on_progress_report(self, req: dict) -> dict:
        return {"report": self.progress_report()}

    def _on_scheduler_report(self, req: dict) -> dict:
        return {"report": self.scheduler_report()}

    def _on_usage_report(self, req: dict) -> dict:
        return {"report": self.usage_report()}

    def _on_events_report(self, req: dict) -> dict:
        return {"report": self.events_report(job=req.get("job"))}

    def _on_dashboard_report(self, req: dict) -> dict:
        return {"report": self.dashboard_report()}

    def scheduler_report(self) -> dict:
        """The master-process arbiter's state (the master and the
        cluster owner share a process, so this is the authoritative
        view client sessions poll)."""
        from raydp_tpu.control import get_arbiter

        return get_arbiter().report()

    def usage_report(self) -> dict:
        """Per-job usage totals folded from the merged cluster view."""
        from raydp_tpu.telemetry import accounting as _acct

        return _acct.usage_report(self.metrics_snapshot())

    def events_report(self, job: Optional[str] = None) -> dict:
        """The cluster event timeline + MTTR report, from the master's
        telemetry-dir shards (or its in-memory ring)."""
        from raydp_tpu.telemetry import events as _events
        from raydp_tpu.telemetry import telemetry_dir

        records = _events.load_event_records(telemetry_dir(), job=job)
        return {"events": records, "mttr": _events.mttr_report(records)}

    def dashboard_report(self) -> dict:
        """The unified flywheel dashboard over the merged cluster view
        (train/ETL/serve/control sections + SLO status + event
        timeline; see :mod:`raydp_tpu.telemetry.dashboard`)."""
        from raydp_tpu.telemetry import dashboard as _dash
        from raydp_tpu.telemetry import events as _events
        from raydp_tpu.telemetry import telemetry_dir

        records = _events.load_event_records(telemetry_dir())
        try:
            scheduler = self.scheduler_report()
        except Exception:
            scheduler = None
        return _dash.build(
            self.metrics_snapshot(), scheduler=scheduler, events=records
        )

    def progress_report(self) -> dict:
        """Live stage progress: the driver-process tracker (DataFrame
        stages run driver-side; workers only execute their tasks) plus
        recent completed-stage stats from the stage store."""
        from raydp_tpu.telemetry.progress import progress, stage_store

        report = progress.report()
        store_snap = stage_store.snapshot()
        report["stage_totals"] = store_snap["totals"]
        report["recent_stage_stats"] = store_snap["stages"][-16:]
        return report

    def health_report(self) -> dict:
        """Aggregated cluster health: per-worker heartbeat age + stall
        flags, plus slowest-rank attribution from the merged timers.

        Designed to fire BEFORE the heartbeat timeout: a wedged task
        does not stop the worker's heartbeat thread, so the stall flag
        arrives on the next beat (~2 s) while ``heartbeat timeout``
        death detection waits ``HEARTBEAT_TIMEOUT_S``.
        """
        from raydp_tpu.telemetry import watchdog as _watchdog

        now = time.monotonic()
        with self._lock:
            workers = {
                wid: {
                    "state": w.state,
                    "node_id": w.node_id,
                    "pid": w.pid,
                    "heartbeat_age_s": round(now - w.last_heartbeat, 3),
                    "stalls": dict(w.stalls),
                }
                for wid, w in self._workers.items()
            }
        stalled = sorted(
            wid for wid, w in workers.items()
            if w["stalls"] and w["state"] == "ALIVE"
        )
        dead = sorted(
            wid for wid, w in workers.items() if w["state"] == "DEAD"
        )
        late = sorted(
            wid for wid, w in workers.items()
            if w["state"] == "ALIVE"
            and w["heartbeat_age_s"] > HEARTBEAT_TIMEOUT_S / 2
        )
        driver = _watchdog.health()
        return {
            "healthy": not (stalled or dead or late)
            and driver.get("healthy", True),
            "workers": workers,
            "stalled_workers": stalled,
            "dead_workers": dead,
            "late_workers": late,
            "slowest_rank": self._slowest_rank(),
            "heartbeat_timeout_s": HEARTBEAT_TIMEOUT_S,
            "driver": driver,
        }

    def _slowest_rank(self) -> Optional[dict]:
        """Straggler attribution from shipped step/task timers (p50:
        robust to one-off spikes; the cross-worker comparison is what
        names the slow rank)."""
        view = self.telemetry.merged()
        slowest: Optional[dict] = None
        for wid, sections in (view.get("workers") or {}).items():
            for key in ("timer/train/step", "timer/worker/task"):
                sec = sections.get(key)
                if not sec or not sec.get("p50_s"):
                    continue
                if slowest is None or sec["p50_s"] > slowest["p50_s"]:
                    slowest = {
                        "worker": wid,
                        "timer": key[len("timer/"):],
                        "p50_s": sec["p50_s"],
                    }
                break  # prefer train/step when a worker has both
        return slowest

    def metrics_snapshot(self) -> dict:
        """Merged cluster metrics: per-worker views (tombstones
        included), the cross-worker aggregate, lifecycle events, and
        this (driver) process's own registry under ``"driver"``."""
        from raydp_tpu.utils.profiling import metrics as _m
        from raydp_tpu.utils.profiling import sample_resource_gauges

        # Refresh the driver's resource gauges at snapshot time (worker
        # gauges arrive pre-sampled on their heartbeats).
        sample_resource_gauges()
        view = self.telemetry.merged()
        view["driver"] = _m.snapshot()
        return view

    def cluster_resources(self) -> dict:
        """Resource introspection (reference:
        python/raydp/ray_cluster_resources.py)."""
        with self._lock:
            alive = [w for w in self._workers.values() if w.state == "ALIVE"]
        total: Dict[str, float] = {}
        for node in self.nodes:
            for k, v in node.resources.items():
                total[k] = total.get(k, 0.0) + v
        used: Dict[str, float] = {}
        for w in alive:
            for k, v in w.resources.items():
                used[k] = used.get(k, 0.0) + v
        return {
            "total": total,
            "used": used,
            "available": {k: total.get(k, 0.0) - used.get(k, 0.0) for k in total},
            "num_nodes": len(self.nodes),
            "num_alive_workers": len(alive),
        }

    # -- monitor --------------------------------------------------------
    def _monitor_loop(self) -> None:
        prev = time.monotonic()
        while not self._monitor_stop.wait(1.0):
            now = time.monotonic()
            prev = self._monitor_tick(now, prev)

    def _monitor_tick(self, now: float, prev: float) -> float:
        """One liveness pass; returns the new ``prev`` timestamp.

        Self-stall defense: if the loop overslept its 1 s period (driver
        process GIL-starved by a big shuffle on a small host), the
        workers' heartbeats were starved by the same cause — their
        staleness is evidence of OUR stall, not their death. Grant the
        oversleep back as grace instead of declaring a massacre.
        """
        oversleep = (now - prev) - 1.0
        if oversleep > 2.0:
            with self._lock:
                for w in self._workers.values():
                    if w.state == "ALIVE":
                        # Clamped: grace covers staleness accrued DURING
                        # the stall; a beat processed near the stall's
                        # end must not end up timestamped in the future
                        # (that would slow genuine death detection by up
                        # to the stall length afterwards).
                        w.last_heartbeat = min(
                            now, w.last_heartbeat + oversleep
                        )
            # Fall through to the stale check: under CHRONIC oversleep
            # (every tick >3 s for many minutes) net staleness still
            # accumulates tick by tick, and skipping the check here
            # would blind death detection for the whole episode — a
            # remote worker that hard-hung at its start would keep
            # receiving tasks indefinitely.
        with self._lock:
            stale = [
                w.worker_id
                for w in self._workers.values()
                if w.state == "ALIVE"
                and now - w.last_heartbeat > HEARTBEAT_TIMEOUT_S
            ]
        for worker_id in stale:
            self.mark_worker_dead(worker_id, reason="heartbeat timeout")
        return now
