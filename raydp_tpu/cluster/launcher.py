"""Pluggable process launchers: how worker/agent processes reach their node.

The reference creates executors on arbitrary cluster nodes through Ray's
actor scheduler (reference: RayExecutorUtils.java:39-61,
RayAppMaster.scala:224-243). Without Ray, launching is a strategy object:

  * ``LocalLauncher`` — subprocess on this machine (single host, and the
    multi-host *simulation* used in tests: node identity is carried by
    ``--node-id``, store namespaces keep "hosts" apart).
  * ``CommandLauncher`` — wraps the argv with a user command builder (ssh,
    kubectl exec, a cluster scheduler CLI …): the same escape hatch as the
    SPMD runner's ``script_prepare_fn`` (reference:
    python/raydp/mpi/mpi_job.py:239-248 custom mpirun script fn).

A launcher returns a Popen-compatible handle (poll/terminate/kill/wait).
"""
from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class LaunchSpec:
    """One process to run somewhere."""

    argv: List[str]  # interpreter-relative: ["-m", "mod", "--flag", …]
    node_id: str
    log_path: Optional[str] = None
    env: Dict[str, str] = field(default_factory=dict)
    cwd: Optional[str] = None


class WorkerLauncher:
    def launch(self, spec: LaunchSpec) -> subprocess.Popen:
        log = None
        if spec.log_path is not None:
            log = open(spec.log_path, "ab")
        try:
            return subprocess.Popen(
                self._command(spec),
                stdout=log if log is not None else subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
                cwd=self._local_cwd(spec),
                env=self._local_env(spec),
            )
        finally:
            if log is not None:
                log.close()

    def _command(self, spec: LaunchSpec) -> List[str]:
        raise NotImplementedError

    def _local_cwd(self, spec: LaunchSpec) -> Optional[str]:
        return spec.cwd

    def _local_env(self, spec: LaunchSpec) -> Dict[str, str]:
        return {**os.environ, **spec.env}


class LocalLauncher(WorkerLauncher):
    """Spawn on this machine with the current interpreter."""

    def _command(self, spec: LaunchSpec) -> List[str]:
        return [sys.executable] + spec.argv


class CommandLauncher(WorkerLauncher):
    """Launch through a user-supplied command builder.

    ``build(spec) -> argv`` returns the full command to exec locally that
    lands the process on ``spec.node_id`` (e.g. ``["ssh", host, …]``).
    The builder is responsible for carrying ``spec.env`` and ``spec.cwd``
    to the remote side; neither is applied to the local wrapper process.
    """

    def __init__(self, build: Callable[[LaunchSpec], List[str]]):
        self._build = build

    def _command(self, spec: LaunchSpec) -> List[str]:
        return self._build(spec)

    def _local_cwd(self, spec: LaunchSpec) -> Optional[str]:
        return None  # cwd is the REMOTE working dir; builder handles it

    def _local_env(self, spec: LaunchSpec) -> Dict[str, str]:
        return dict(os.environ)


def ssh_launcher(
    hosts: Dict[str, str], python: str = "python3"
) -> CommandLauncher:
    """A CommandLauncher that ssh-es to ``hosts[node_id]`` and runs the
    process there: cd to the spec cwd (so ``-m raydp_tpu...`` resolves
    from a repo checkout) and forward the spec env inline."""
    import shlex

    def build(spec: LaunchSpec) -> List[str]:
        host = hosts[spec.node_id]
        parts = []
        if spec.cwd:
            parts.append(f"cd {shlex.quote(spec.cwd)} &&")
        if spec.env:
            parts.append(
                "env " + " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in spec.env.items()
                )
            )
        parts.append(
            " ".join([python] + [shlex.quote(a) for a in spec.argv])
        )
        return ["ssh", host, " ".join(parts)]

    return CommandLauncher(build)
