"""Client mode: a second driver attaching to a live AppMaster.

Parity with the reference's Ray-client story, where every test runs both
direct and through ``ray://`` (reference: python/raydp/tests/
conftest.py:42-49) and a driver can live inside another process
(test_spark_cluster.py:38-57). Here the whole control plane is already
gRPC, so a remote driver is a set of thin proxies:

  * object writes → ``PutObject`` on the master (driver-node store);
  * object reads  → the standard resolver (master directory → node agent
    fetch; the client has no shm of its own, so every read is remote);
  * stage tasks   → shipped straight to workers' RunTask endpoints, with
    the same retry discipline as the in-process Cluster;
  * lifecycle RPCs (ListWorkers, ClusterResources, TransferToHolder…) →
    the master service.

``raydp_tpu.connect(addr)`` installs a ClientSession as the process
session, so the whole DataFrame/MLDataset/estimator surface works
unchanged. Disconnecting never tears the remote cluster down.
"""
from __future__ import annotations

import itertools
import logging
import os
import random
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import pyarrow as pa

from raydp_tpu.cluster.master import SERVICE, WorkerInfo
from raydp_tpu.cluster.rpc import RpcClient, RpcError
from raydp_tpu.store.object_store import OWNER_HOLDER, ObjectRef
from raydp_tpu.store.resolver import ObjectResolver

logger = logging.getLogger(__name__)


#: Sentinel outcome: the envelope thread resolved its futures inline
#: (per-envelope streaming) — nothing left for the retry joiner to do.
_BATCH_DONE = object()


class ClientError(RuntimeError):
    pass


def _retry_idempotent(fn: Callable[[], Any], what: str) -> Any:
    """Run an idempotent master RPC with jittered exponential backoff.

    A briefly unreachable master (restarting container, transient
    partition, LB blip) must not fail the client's first RPC — but only
    IDEMPOTENT calls may be retried: a timed-out mutation could have
    been applied, and re-sending it would double-apply. Read-only calls
    (Ping, ListWorkers, GetObjectMeta, …) are safe to re-send verbatim.

    ``RAYDP_TPU_CLIENT_RETRIES`` attempts (default 4) with base delay
    ``RAYDP_TPU_CLIENT_BACKOFF_S`` (default 0.25) doubling per attempt,
    plus up to 25% jitter so a fleet of reconnecting clients doesn't
    stampede the recovering master in lockstep.
    """
    import grpc

    try:
        retries = max(0, int(os.environ.get("RAYDP_TPU_CLIENT_RETRIES", "4")))
    except ValueError:
        retries = 4
    try:
        backoff = float(os.environ.get("RAYDP_TPU_CLIENT_BACKOFF_S", "0.25"))
    except ValueError:
        backoff = 0.25
    attempt = 0
    while True:
        try:
            return fn()
        except grpc.RpcError as exc:
            # Transport-level failure only: an RpcError (remote handler
            # raised) means the master IS reachable — retrying a
            # handler exception would just repeat it.
            if attempt >= retries:
                raise
            delay = backoff * (2 ** attempt)
            delay *= 1.0 + random.uniform(0.0, 0.25)
            attempt += 1
            code = getattr(exc, "code", lambda: "?")()
            logger.warning(
                "client: %s unreachable (%s); retry %d/%d in %.2fs",
                what, code, attempt, retries, delay,
            )
            time.sleep(delay)


class _RemoteStore:
    """Duck-types the DirectoryStore surface the executor layer uses,
    proxying every operation to the master."""

    def __init__(self, master: RpcClient, namespace: str):
        self.namespace = namespace
        self.node_id = f"client-{os.getpid()}"  # never matches a data node
        self._master = master

    def put(self, data, owner: str = OWNER_HOLDER, num_rows: int = -1) -> ObjectRef:
        reply = self._master.call(
            "PutObject",
            {"data": bytes(data), "owner": owner, "num_rows": num_rows},
            timeout=120.0,
        )
        return reply["ref"]

    def put_arrow_table(self, table: pa.Table, owner: str = OWNER_HOLDER) -> ObjectRef:
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return self.put(
            sink.getvalue().to_pybytes(), owner=owner, num_rows=table.num_rows
        )

    def get_ref(self, object_id: str) -> Optional[ObjectRef]:
        reply = self._master.call("GetObjectMeta", {"object_id": object_id})
        return reply.get("ref")

    def transfer_to_holder(self, ref: ObjectRef) -> ObjectRef:
        return self._master.call("TransferToHolder", {"ref": ref})["ref"]

    def delete(self, ref_or_id) -> bool:
        object_id = (
            ref_or_id.object_id
            if isinstance(ref_or_id, ObjectRef)
            else ref_or_id
        )
        reply = self._master.call("DeleteObject", {"object_id": object_id})
        return bool(reply.get("deleted"))

    def contains(self, ref_or_id) -> bool:
        object_id = (
            ref_or_id.object_id
            if isinstance(ref_or_id, ObjectRef)
            else ref_or_id
        )
        return self.get_ref(object_id) is not None

    def refs(self) -> List[ObjectRef]:
        return self._master.call("ListObjects", {})["refs"]

    # Resolver local-store protocol: the client holds no segments.
    def get_buffer(self, ref_or_id):
        raise KeyError("client has no local segments")

    def get_bytes(self, ref_or_id):
        raise KeyError("client has no local segments")

    def get_arrow_table(self, ref_or_id):
        raise KeyError("client has no local segments")


class _RemoteMaster:
    """The ``cluster.master`` facet a client sees."""

    def __init__(self, client: RpcClient, namespace: str):
        self._client = client
        self.namespace = namespace
        self.store = _RemoteStore(client, namespace)

    # Read-only lookups retry through master blips (idempotent: the
    # identical request can be re-sent with no double-apply risk).
    # Mutations (PutObject, RegisterObject, TransferToHolder) do NOT —
    # a timed-out mutation may have landed, and the caller must decide.
    def object_meta(self, object_id: str):
        reply = _retry_idempotent(
            lambda: self._client.call("GetObjectMeta", {"object_id": object_id}),
            "master GetObjectMeta",
        )
        return reply.get("ref"), reply.get("agent")

    def alive_workers(self) -> List[WorkerInfo]:
        workers = _retry_idempotent(
            lambda: self._client.call("ListWorkers", {}),
            "master ListWorkers",
        )["workers"]
        return [w for w in workers if w.state == "ALIVE"]

    def cluster_resources(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("ClusterResources", {}),
            "master ClusterResources",
        )

    def metrics_snapshot(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("MetricsSnapshot", {}),
            "master MetricsSnapshot",
        )["snapshot"]

    def health_report(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("HealthReport", {}),
            "master HealthReport",
        )["report"]

    def progress_report(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("ProgressReport", {}),
            "master ProgressReport",
        )["report"]

    def scheduler_report(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("SchedulerReport", {}),
            "master SchedulerReport",
        )["report"]

    def usage_report(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("UsageReport", {}),
            "master UsageReport",
        )["report"]

    def events_report(self, job: Optional[str] = None) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("EventsReport", {"job": job}),
            "master EventsReport",
        )["report"]

    def dashboard_report(self) -> dict:
        return _retry_idempotent(
            lambda: self._client.call("DashboardReport", {}),
            "master DashboardReport",
        )["report"]

    def mark_worker_dead(self, worker_id: str, reason: str = "") -> None:
        # Best-effort: the real master's own monitors are authoritative;
        # a client merely stops routing to the worker.
        logger.warning("client: worker %s unreachable (%s)", worker_id, reason)


class RemoteCluster:
    """Duck-types the Cluster surface used by executors/datasets."""

    _WORKER_TTL = 1.0  # seconds of ListWorkers caching

    def __init__(self, master_address: str):
        self.master_address = master_address
        self._client = RpcClient(master_address, SERVICE)
        # The connect handshake retries: attaching while the master is
        # briefly unreachable (restart, partition) should wait it out,
        # not fail the session's very first RPC. Ping is idempotent.
        reply = _retry_idempotent(
            lambda: self._client.call("Ping", {}),
            f"master {master_address}",
        )
        self.namespace = reply["namespace"]
        self.master = _RemoteMaster(self._client, self.namespace)
        self._pool = ThreadPoolExecutor(max_workers=32)
        self._worker_clients: Dict[str, RpcClient] = {}
        self._workers_cache: List[WorkerInfo] = []
        self._workers_stamp = 0.0
        self._lock = threading.RLock()
        self._resolver: Optional[ObjectResolver] = None
        # Round-robin cursor for unpinned tasks (parity with the in-process
        # Cluster._pick_worker): without it every attempt-0 submit lands on
        # workers[0] and client drivers load one worker.
        self._rr = itertools.count()

    # -- object access --------------------------------------------------
    @property
    def resolver(self) -> ObjectResolver:
        if self._resolver is None:
            self._resolver = ObjectResolver(
                self.master.store, self.master.object_meta
            )
        return self._resolver

    # -- introspection --------------------------------------------------
    def alive_workers(self) -> List[WorkerInfo]:
        now = time.monotonic()
        with self._lock:
            if now - self._workers_stamp < self._WORKER_TTL:
                return list(self._workers_cache)
        workers = self.master.alive_workers()
        with self._lock:
            self._workers_cache = workers
            self._workers_stamp = now
        return list(workers)

    def cluster_resources(self) -> dict:
        return self.master.cluster_resources()

    def metrics_snapshot(self) -> dict:
        """The remote master's merged telemetry view (its ``driver`` entry
        is the cluster-owning process, not this client)."""
        return self.master.metrics_snapshot()

    def prometheus_metrics(self) -> str:
        """Render the remote view locally — the exposition text never
        crosses the wire, only the pickled snapshot does."""
        from raydp_tpu.telemetry import render_prometheus

        return render_prometheus(self.metrics_snapshot())

    def trace_report(self) -> Optional[dict]:
        """Analyze the merged trace like ``Cluster.trace_report`` —
        meaningful when this client shares ``RAYDP_TPU_TELEMETRY_DIR``
        with the cluster host (same machine or shared filesystem);
        None when the directory is not configured here."""
        from raydp_tpu.telemetry import analyze, flush_spans, telemetry_dir

        directory = telemetry_dir()
        if directory is None:
            return None
        flush_spans()
        return analyze.trace_report(directory)

    def health_report(self) -> dict:
        """The remote master's aggregated cluster health (same shape as
        ``Cluster.health_report``; its ``driver`` entry describes the
        cluster-owning process, not this client)."""
        return self.master.health_report()

    def progress_report(self) -> dict:
        """Stage progress as seen from THIS client (DataFrame stages
        run on the submitting driver), with the cluster-owning
        process's report attached under ``"cluster"``."""
        from raydp_tpu.telemetry.progress import progress, stage_store

        report = progress.report()
        report["stage_totals"] = stage_store.snapshot()["totals"]
        try:
            report["cluster"] = self.master.progress_report()
        except Exception:
            pass  # older master without the ProgressReport handler
        return report

    def scheduler_report(self) -> Optional[dict]:
        """The remote master's arbiter state (same shape as
        ``Cluster.scheduler_report``). Retries through master blips —
        a dashboard polling during a restart waits it out instead of
        hard-failing. None against an older master without the
        handler."""
        try:
            return self.master.scheduler_report()
        except Exception:
            return None  # older master without the SchedulerReport handler

    def usage_report(self) -> Optional[dict]:
        """Per-job usage totals folded on the cluster owner (same shape
        as ``Cluster.usage_report``). Retries through master blips;
        None against an older master without the handler."""
        try:
            return self.master.usage_report()
        except Exception:
            return None  # older master without the UsageReport handler

    def events_report(self, job: Optional[str] = None) -> Optional[dict]:
        """The cluster event timeline + MTTR from the master's shards
        (same shape as ``Cluster.events_report``). Retries through
        master blips; None against an older master without the
        handler."""
        try:
            return self.master.events_report(job=job)
        except Exception:
            return None  # older master without the EventsReport handler

    def dashboard_report(self) -> Optional[dict]:
        """The unified flywheel dashboard rendered on the cluster owner
        (same shape as ``Cluster.dashboard_report``). Retries through
        master blips; None against an older master without the
        handler."""
        try:
            return self.master.dashboard_report()
        except Exception:
            return None  # older master without the DashboardReport handler

    def capture_profile(
        self, seconds: float = 3.0, out_dir: Optional[str] = None
    ) -> Optional[dict]:
        """Client-mode twin of ``Cluster.capture_profile``: fan
        ProfileRequest out to every alive worker directly (the client
        already holds worker stubs for task submission) and merge the
        archives here. The client process itself is not captured — it
        runs no device work. Worker archives staged in the shm store
        are resolved through the normal data plane."""
        from raydp_tpu.telemetry import device_profiler

        workers = self.alive_workers()
        if not workers:
            return None
        payloads: Dict[str, dict] = {}
        errors: Dict[str, str] = {}

        def _one(info: WorkerInfo) -> None:
            try:
                payloads[info.worker_id] = self._worker_client(info).call(
                    "ProfileRequest", {"seconds": seconds},
                    timeout=seconds + 30.0,
                )
            except Exception as exc:
                errors[info.worker_id] = str(exc)

        threads = [
            threading.Thread(target=_one, args=(w,), daemon=True)
            for w in workers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=seconds + 60.0)
        if not payloads:
            raise ClientError(
                f"profile capture failed on every worker: {errors}"
            )
        ordered = [payloads[wid] for wid in sorted(payloads)]
        for payload in ordered:
            ref = payload.pop("ref", None)
            if ref is not None and "zip" not in payload:
                payload["zip"] = self.resolver.get_bytes(ref)
        merged = device_profiler.merge_rank_traces(ordered, out_dir)
        if errors:
            merged["errors"] = errors
        return merged

    # -- task submission ------------------------------------------------
    def submit(self, fn, *args, worker_id=None, timeout=300.0, **kwargs):
        return self.submit_async(
            fn, *args, worker_id=worker_id, timeout=timeout, **kwargs
        ).result()

    def submit_async(
        self,
        fn: Callable,
        *args,
        worker_id: Optional[str] = None,
        timeout: float = 300.0,
        retries: int = 2,
        data_args=(),
        meta_sink: Optional[Callable] = None,
        **kwargs,
    ) -> Future:
        """Like ``Cluster.submit_async``; ``data_args`` tables are staged
        into the cluster's driver-node store via PutObject (a client has
        no shm of its own — one hop to the master, after which workers
        resolve them through the normal data plane) and only refs ride
        the per-task envelope."""
        staged = self._stage_data_args(data_args)
        # One id for ALL delivery attempts of this submission: a
        # reconnect retry after UNAVAILABLE may land on a worker that
        # already executed (or is still executing) the first delivery —
        # the worker-side dedup cache keyed on this id turns the
        # re-delivery into a wait-for-the-original instead of a second
        # execution (serve dispatches are not idempotent).
        payload = {
            "fn": cloudpickle.dumps(fn),
            "args": args,
            "kwargs": kwargs,
            "request_id": uuid.uuid4().hex,
        }
        if staged:
            payload["data_refs"] = staged
        # Capture the submitting thread's trace context — the RPC fires
        # from a pool thread (same reasoning as Cluster.submit_async).
        from raydp_tpu.telemetry import propagation as _prop

        trace_ctx = _prop.current_context()

        def run():
            import grpc

            preferred = worker_id
            rr = next(self._rr)
            last: Optional[BaseException] = None
            for attempt in range(retries + 1):
                workers = self.alive_workers()
                target = None
                if preferred is not None:
                    target = next(
                        (w for w in workers if w.worker_id == preferred), None
                    )
                if target is None:
                    if not workers:
                        last = ClientError("no alive workers")
                        time.sleep(0.3 * (attempt + 1))
                        continue
                    target = workers[(rr + attempt) % len(workers)]
                client = self._worker_client(target)
                try:
                    reply = client.call("RunTask", payload, timeout=timeout)
                    if meta_sink is not None:
                        try:
                            meta_sink(
                                0, target.worker_id,
                                reply.get("exec_s", 0.0),
                            )
                        except Exception:
                            pass
                    return reply["result"]
                except grpc.RpcError as exc:
                    code = exc.code()
                    if code == grpc.StatusCode.UNAVAILABLE:
                        with self._lock:
                            self._workers_stamp = 0.0  # force refresh
                        preferred = None
                        last = ClientError(
                            f"worker {target.worker_id} unreachable"
                        )
                        continue
                    raise ClientError(
                        f"task RPC to {target.worker_id} failed: {code}"
                    ) from exc
            raise ClientError(
                f"task failed after {retries + 1} attempts: {last}"
            ) from last

        def traced_run():
            try:
                with _prop.propagated(trace_ctx):
                    return run()
            finally:
                self._discard_staged(staged)

        return self._pool.submit(traced_run)

    # -- batched submission (one envelope per worker) --------------------
    def submit_batch(self, specs, timeout: float = 300.0,
                     retries: int = 2,
                     meta_sink: Optional[Callable] = None) -> List[Future]:
        """Client-mode twin of ``Cluster.submit_batch``: one RunTaskBatch
        envelope per worker, one Future per spec (in order).
        ``meta_sink(spec_index, worker_id, exec_s)`` fires before the
        matching future resolves, mirroring the in-process Cluster."""
        futures: List[Future] = [Future() for _ in specs]
        if not specs:
            return futures
        from raydp_tpu.telemetry import propagation as _prop

        trace_ctx = _prop.current_context()

        def orchestrate():
            with _prop.propagated(trace_ctx):
                try:
                    self._run_batch(
                        list(specs), futures, timeout, retries, meta_sink
                    )
                except BaseException as exc:  # noqa: BLE001
                    for f in futures:
                        if not f.done():
                            f.set_exception(exc)

        self._pool.submit(orchestrate)
        return futures

    def _run_batch(self, specs, futures, timeout, retries, meta_sink=None):
        import grpc

        staged = [self._stage_data_args(s.data_args) for s in specs]
        try:
            pending = list(range(len(specs)))
            last: Optional[BaseException] = None
            for attempt in range(retries + 1):
                workers = self.alive_workers()
                if not workers:
                    last = ClientError("no alive workers")
                    time.sleep(0.3 * (attempt + 1))
                    continue
                by_id = {w.worker_id: w for w in workers}
                groups: Dict[str, List[int]] = {}
                for i in pending:
                    pref = specs[i].worker_id if attempt == 0 else None
                    if pref not in by_id:
                        pref = workers[
                            (next(self._rr)) % len(workers)
                        ].worker_id
                    groups.setdefault(pref, []).append(i)
                results: Dict[str, Any] = {}

                def call_group(wid, idxs):
                    try:
                        client = self._worker_client(by_id[wid])
                        fn_blobs, fn_index, tasks = [], {}, []
                        for i in idxs:
                            spec = specs[i]
                            slot = fn_index.get(id(spec.fn))
                            if slot is None:
                                slot = len(fn_blobs)
                                fn_blobs.append(cloudpickle.dumps(spec.fn))
                                fn_index[id(spec.fn)] = slot
                            task = {"fn": slot, "args": spec.args,
                                    "kwargs": spec.kwargs}
                            if staged[i]:
                                task["data_refs"] = staged[i]
                            tasks.append(task)
                        reply = client.call(
                            "RunTaskBatch",
                            {"fns": fn_blobs, "tasks": tasks},
                            timeout=timeout,
                        )
                        # Per-envelope streaming: resolve this worker's
                        # futures the moment IT replies, not after the
                        # slowest envelope joins.
                        for i, res in zip(idxs, reply["results"]):
                            if res.get("ok"):
                                if meta_sink is not None:
                                    try:
                                        meta_sink(
                                            i, wid, res.get("exec_s", 0.0)
                                        )
                                    except Exception:
                                        pass
                                futures[i].set_result(res.get("value"))
                            else:
                                futures[i].set_exception(RpcError(
                                    f"batched task failed on {wid}: "
                                    f"{res.get('error')}\n"
                                    f"{res.get('traceback', '')}"
                                ))
                        results[wid] = _BATCH_DONE
                    except grpc.RpcError as exc:
                        if exc.code() in (grpc.StatusCode.UNAVAILABLE,
                                          grpc.StatusCode.CANCELLED):
                            with self._lock:
                                self._workers_stamp = 0.0  # force refresh
                            results[wid] = ClientError(
                                f"worker {wid} unreachable"
                            )
                        else:
                            results[wid] = ClientError(
                                f"batch RPC to {wid} failed: {exc.code()}"
                            )
                            results[wid].__cause__ = exc
                            results[wid]._hard = True
                    except BaseException as exc:  # noqa: BLE001
                        exc._hard = True
                        results[wid] = exc

                threads = [
                    threading.Thread(target=call_group, args=(wid, idxs),
                                     daemon=True)
                    for wid, idxs in groups.items()
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                next_pending: List[int] = []
                for wid, idxs in groups.items():
                    outcome = results.get(wid)
                    if outcome is _BATCH_DONE:
                        continue
                    if isinstance(outcome, BaseException):
                        if getattr(outcome, "_hard", False):
                            raise outcome
                        last = outcome
                        next_pending.extend(idxs)
                        continue
                    raise ClientError(
                        f"batch envelope to {wid} vanished without an "
                        f"outcome"
                    )
                pending = next_pending
                if not pending:
                    return
            for i in pending:
                if not futures[i].done():
                    futures[i].set_exception(ClientError(
                        f"batched task failed after {retries + 1} "
                        f"attempts: {last}"
                    ))
        finally:
            for refs in staged:
                self._discard_staged(refs)

    # -- data-plane staging ----------------------------------------------
    def _stage_data_args(self, tables) -> List[ObjectRef]:
        if not tables:
            return []
        store = self.master.store
        return [store.put_arrow_table(t) for t in tables]

    def _discard_staged(self, refs) -> None:
        for ref in refs or ():
            try:
                self.master.store.delete(ref)
            except Exception:
                pass

    def _worker_client(self, info: WorkerInfo) -> RpcClient:
        with self._lock:
            client = self._worker_clients.get(info.worker_id)
            if client is None or client.address != info.address:
                client = RpcClient(info.address, "raydp.Worker")
                self._worker_clients[info.worker_id] = client
            return client

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            for client in self._worker_clients.values():
                client.close()
            self._worker_clients.clear()
        if self._resolver is not None:
            self._resolver.close()
        self._client.close()


class ClientSession:
    """Session facade for a remote driver. ``stop()`` disconnects only —
    the cluster belongs to the process that ran ``init()``."""

    # context.init() inspects this when replacing a stopped session; a
    # client never owns holder objects, so it is always "released".
    _holder_released = True

    def __init__(self, master_address: str):
        self.cluster = RemoteCluster(master_address)
        self._closed = False

    @property
    def stopped(self) -> bool:
        return self._closed

    def stop(self, del_obj_holder: bool = True, fast: bool = False) -> None:
        if not self._closed:
            self.cluster.close()
            self._closed = True

    disconnect = stop
