"""ETL worker process entry point.

Role parity with the reference's executor backend
(reference: core/.../executor/RayCoarseGrainedExecutorBackend.scala:38-262):
a separately spawned process that registers with the AppMaster (with
retries, :58-81), runs tasks shipped from the driver, heartbeats, and
exits on Stop or on master disappearance.

Tasks are cloudpickled callables ``fn(worker_ctx, *args)`` (the MPI
subsystem's function-shipping design, reference:
python/raydp/mpi/mpi_worker.py:75-96). Results return inline; large Arrow
results go through the shm object store and return ObjectRefs.
"""
from __future__ import annotations

import argparse
import atexit
import logging
import os
import sys
import threading
import time
import traceback
from collections import OrderedDict

import cloudpickle

from raydp_tpu import fault as _fault
from raydp_tpu.cluster.rpc import RpcClient, RpcServer
from raydp_tpu.store.object_store import ObjectStore
from raydp_tpu.telemetry import MetricsShipper, flush_spans, span
from raydp_tpu.telemetry import accounting as _acct
from raydp_tpu.telemetry import flight_recorder as _flight
from raydp_tpu.telemetry import logs as _logs
from raydp_tpu.telemetry import propagation as trace_prop
from raydp_tpu.telemetry import watchdog as _watchdog
from raydp_tpu.utils.profiling import metrics

logger = logging.getLogger(__name__)

WORKER_SERVICE = "raydp.Worker"
REGISTER_RETRIES = 3
# Completed-task replies kept for duplicate-delivery detection. Sized
# for the realistic retry window (seconds), not task history.
_DEDUP_CAPACITY = 1024
# A duplicate that arrives while the original is still executing waits
# this long for the first execution to finish before giving up.
_DEDUP_WAIT_S = 300.0


class WorkerContext:
    """Handed to every shipped task as its first argument."""

    def __init__(self, worker_id: str, node_id: str, store: ObjectStore,
                 master: RpcClient):
        self.worker_id = worker_id
        self.node_id = node_id
        self.store = store
        self._master = master
        from raydp_tpu.store.resolver import ObjectResolver

        self.resolver = ObjectResolver(store, self._object_meta)

    def _object_meta(self, object_id: str):
        reply = self._master.call("GetObjectMeta", {"object_id": object_id})
        return reply.get("ref"), reply.get("agent")

    def put_table(self, table, holder: bool = False):
        """Store an Arrow table; returns ObjectRef.

        Owned by this worker by default (dies with it); ``holder=True``
        writes it holder-owned up front (ingest data that must survive pool
        shrinks). The ref is registered in the master's object directory so
        owner lifetime is enforced cluster-wide (reference: executor-side
        Ray.put with optional owner, ObjectStoreWriter.scala:58-79).
        """
        from raydp_tpu.store.object_store import OWNER_HOLDER

        owner = OWNER_HOLDER if holder else self.worker_id
        ref = self.store.put_arrow_table(table, owner=owner)
        self._master.call("RegisterObject", {"ref": ref})
        return ref

    def put_bytes(self, data) -> "ObjectRef":
        ref = self.store.put(data, owner=self.worker_id)
        self._master.call("RegisterObject", {"ref": ref})
        return ref

    def get_table(self, ref):
        """Read an Arrow table from anywhere in the cluster: local shm
        zero-copy, or a gRPC pull from the owning node's store agent."""
        return self.resolver.get_arrow_table(ref)

    def get_bytes(self, ref):
        return self.resolver.get_bytes(ref)


class Worker:
    def __init__(self, worker_id: str, master_address: str, node_id: str,
                 resources: dict, bind_host: str = "127.0.0.1"):
        self.worker_id = worker_id
        self.node_id = node_id
        self.resources = resources
        # Generous default timeout: control RPCs (RegisterObject) must
        # survive a driver process saturated by a big shuffle on a small
        # host — a slow master is not a dead master.
        self.master = RpcClient(
            master_address, "raydp.AppMaster", timeout=120.0
        )
        self.store: ObjectStore = None  # namespace learned at registration
        self.ctx: WorkerContext = None
        self._stop_event = threading.Event()
        # Tasks in flight right now. A worker mid-task must never decide
        # the master is gone and exit: on a core-starved host (one CPU,
        # many shuffle processes) heartbeat round-trips stall for tens of
        # seconds precisely WHILE tasks run, and a mid-task exit cancels
        # the in-flight RunTask on the driver side.
        self._busy = 0
        self._busy_lock = threading.Lock()
        # Monotonic count of tasks this process has started (single and
        # batched alike) — the index the fault plan's kill task= clause
        # matches against.
        self._task_seq = 0
        # At-most-once execution for id-carrying tasks: request_id ->
        # {"done": Event, "reply": dict | None, "error": str | None}.
        # A client reconnect retry that re-delivers an envelope this
        # process already saw waits for (or returns) the first
        # execution's outcome instead of running the fn twice. Bounded:
        # oldest entries age out past _DEDUP_CAPACITY.
        self._dedup: "OrderedDict[str, dict]" = OrderedDict()
        self._dedup_lock = threading.Lock()
        # Telemetry: each heartbeat carries the registry sections that
        # changed since the previous beat (delta-encoded snapshot).
        self._shipper = MetricsShipper()
        # The RPC server is up before registration completes, and the master
        # lists this worker ALIVE the moment RegisterWorker returns — so a
        # task can arrive while ctx is still being built. Gate on readiness.
        self._ready = threading.Event()
        # Batched tasks run concurrently on this pool (pyarrow releases
        # the GIL for the heavy kernels, so same-worker tasks in one
        # envelope keep the intra-worker parallelism that per-partition
        # RPCs used to get from separate gRPC handler threads).
        self._task_pool = None
        self._task_pool_lock = threading.Lock()
        self._server = RpcServer(
            WORKER_SERVICE,
            {
                "RunTask": self._on_run_task,
                "RunTaskBatch": self._on_run_task_batch,
                "Ping": lambda req: {"pong": True, "worker_id": self.worker_id},
                "Stop": self._on_stop,
                "ProfileRequest": self._on_profile,
            },
            host=bind_host,
        )

    def register(self) -> None:
        last_exc = None
        for attempt in range(REGISTER_RETRIES):
            try:
                reply = self.master.call(
                    "RegisterWorker",
                    {
                        "worker_id": self.worker_id,
                        "address": self._server.address,
                        "pid": os.getpid(),
                        "node_id": self.node_id,
                        "resources": self.resources,
                    },
                )
                namespace = reply["namespace"]
                self.store = ObjectStore(
                    namespace=namespace, node_id=self.node_id
                )
                from raydp_tpu.store.object_store import (
                    set_current_resolver,
                    set_current_store,
                )

                set_current_store(self.store)
                self.ctx = WorkerContext(
                    self.worker_id, self.node_id, self.store, self.master
                )
                set_current_resolver(self.ctx.resolver)
                self._ready.set()
                return
            except Exception as exc:
                last_exc = exc
                time.sleep(0.5 * (attempt + 1))
        raise RuntimeError(
            f"worker {self.worker_id} failed to register after "
            f"{REGISTER_RETRIES} attempts: {last_exc}"
        )

    def _on_run_task(self, req: dict) -> dict:
        rid = req.get("request_id")
        if rid is None:
            return self._execute_task(req)
        with self._dedup_lock:
            entry = self._dedup.get(rid)
            owner = entry is None
            if owner:
                entry = {
                    "done": threading.Event(), "reply": None, "error": None,
                }
                self._dedup[rid] = entry
                while len(self._dedup) > _DEDUP_CAPACITY:
                    self._dedup.popitem(last=False)
            else:
                self._dedup.move_to_end(rid)
        if not owner:
            # Re-delivery of an envelope this process already has:
            # return the first execution's outcome (waiting it out if
            # still in flight) — never run the fn a second time.
            metrics.counter_add("worker/dup_tasks")
            if not entry["done"].wait(timeout=_DEDUP_WAIT_S):
                raise RuntimeError(
                    f"duplicate delivery of task {rid}: original "
                    f"execution still in flight after {_DEDUP_WAIT_S:.0f}s"
                )
            if entry["error"] is not None:
                raise RuntimeError(entry["error"])
            return entry["reply"]
        try:
            reply = self._execute_task(req)
        except Exception as exc:
            entry["error"] = f"{type(exc).__name__}: {exc}"
            entry["done"].set()
            raise
        entry["reply"] = reply
        entry["done"].set()
        return reply

    def _execute_task(self, req: dict) -> dict:
        # Busy goes up FIRST: between this handler starting and fn
        # deserializing, the heartbeat thread must already see the task
        # — an exit decision in that setup window would cancel it.
        with self._busy_lock:
            self._busy += 1
        try:
            if not self._ready.wait(timeout=15.0):
                raise RuntimeError(
                    "worker context not ready (registration hung)"
                )
            fn = cloudpickle.loads(req["fn"])
            args = req.get("args", ())
            kwargs = req.get("kwargs", {})
            # data_args travel the data plane: the envelope carries refs,
            # the tables are resolved here (zero-copy from local shm when
            # co-located with the submitter, chunked agent fetch if not).
            data = self._resolve_data_refs(req.get("data_refs", ()))
            self._fault_task_hook()
            metrics.counter_add("worker/tasks")
            _flight.record("task", "start", worker_id=self.worker_id)
            # RpcServer already installed the caller's traceparent as
            # this handler thread's ambient context, so this span — and
            # any span the task body opens — lands in the driver's
            # job trace, under the submitting stage span. The inflight
            # bracket is the watchdog's stall signal: a wedged task
            # body shows up as component "worker/task" — at the long-op
            # threshold, since a healthy task may run for minutes.
            t0 = time.perf_counter()
            with _watchdog.inflight(
                "worker/task", worker_id=self.worker_id,
                stall_after_s=_watchdog.long_stall_s(),
            ):
                with span("worker/task", worker_id=self.worker_id):
                    with metrics.timer("worker/task").time():
                        result = fn(self.ctx, *args, *data, **kwargs)
            _flight.record("task", "end", worker_id=self.worker_id)
            exec_s = time.perf_counter() - t0
            # RpcServer._wrap installed the caller's job scope, so
            # host-CPU task seconds bill to the job that submitted the
            # task, not to this worker's own identity.
            _acct.add_usage(_acct.TASK_SECONDS, exec_s)
            # exec_s lets the driver split stage wall into queue vs
            # execution (stage-stats attribution) with no extra RPC.
            return {"result": result, "exec_s": exec_s}
        except Exception:
            # Let RpcServer._wrap serialize the failure uniformly.
            raise
        finally:
            with self._busy_lock:
                self._busy -= 1

    def _resolve_data_refs(self, refs):
        return [self.ctx.get_table(r) for r in refs]

    def _fault_task_hook(self) -> None:
        """Fault-plan hook at each task start (kill worker=…,task=K)."""
        with self._busy_lock:
            seq = self._task_seq
            self._task_seq += 1
        if _fault.active():
            _fault.on_task(self.worker_id, seq)

    def _pool(self):
        with self._task_pool_lock:
            if self._task_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._task_pool = ThreadPoolExecutor(
                    max_workers=max(4, os.cpu_count() or 4),
                    thread_name_prefix=f"{self.worker_id}-task",
                )
            return self._task_pool

    def _on_run_task_batch(self, req: dict) -> dict:
        """One envelope, many tasks (the driver's submit_batch).

        Each distinct fn arrives once in ``fns``; tasks reference it by
        slot. Tasks run concurrently on the worker task pool and each
        reports per-task ``{"ok": ...}`` so one bad partition fails only
        its own future, not its siblings in the envelope.
        """
        with self._busy_lock:
            self._busy += 1
        try:
            if not self._ready.wait(timeout=15.0):
                raise RuntimeError(
                    "worker context not ready (registration hung)"
                )
            fns = [cloudpickle.loads(b) for b in req["fns"]]
            tasks = req.get("tasks", ())
            metrics.counter_add("worker/tasks", len(tasks))
            metrics.counter_add("worker/task_batches")
            _flight.record("task", "batch_start", worker_id=self.worker_id,
                           tasks=len(tasks))
            # Task-pool threads don't inherit this handler thread's
            # propagated traceparent — re-propagate it so per-task spans
            # still parent under the driver's stage span. The job scope
            # crosses the same thread boundary the same way.
            batch_ctx = trace_prop.current_context()
            batch_job = _acct.current_job()

            def run_one(task: dict) -> dict:
                try:
                    fn = fns[task["fn"]]
                    args = task.get("args", ())
                    kwargs = task.get("kwargs", {})
                    data = self._resolve_data_refs(task.get("data_refs", ()))
                    self._fault_task_hook()
                    t0 = time.perf_counter()
                    with trace_prop.propagated(batch_ctx), \
                            _acct.job_scope(batch_job):
                        with span("worker/task", worker_id=self.worker_id):
                            with metrics.timer("worker/task").time():
                                value = fn(self.ctx, *args, *data, **kwargs)
                        exec_s = time.perf_counter() - t0
                        _acct.add_usage(_acct.TASK_SECONDS, exec_s)
                    return {"ok": True, "value": value, "exec_s": exec_s}
                except Exception as exc:
                    return {
                        "ok": False,
                        "error": f"{type(exc).__name__}: {exc}",
                        "traceback": traceback.format_exc(),
                    }

            with _watchdog.inflight(
                "worker/task", worker_id=self.worker_id,
                stall_after_s=_watchdog.long_stall_s(),
            ):
                if len(tasks) == 1:
                    results = [run_one(tasks[0])]
                else:
                    results = list(self._pool().map(run_one, tasks))
            _flight.record("task", "batch_end", worker_id=self.worker_id,
                           tasks=len(tasks))
            return {"results": results}
        finally:
            with self._busy_lock:
                self._busy -= 1

    def _on_profile(self, req: dict) -> dict:
        """Gang trace capture on this ETL worker. Runs on the RPC
        handler thread, concurrent with any in-flight tasks — the trace
        samples them live. The zip ships through the shm object store
        when the worker is registered (``{"ref": ...}``); inline bytes
        are the pre-registration fallback."""
        from raydp_tpu.telemetry import device_profiler

        seconds = float(req.get("seconds", 3.0))
        _flight.record("profile", "start", worker_id=self.worker_id,
                       seconds=seconds)
        payload = device_profiler.capture_trace_archive(seconds)
        payload["worker_id"] = self.worker_id
        if self._ready.is_set():
            try:
                blob = payload.pop("zip")
                payload["ref"] = self.ctx.put_bytes(blob)
            except Exception:
                payload["zip"] = blob  # store unavailable: inline
        _flight.record("profile", "end", worker_id=self.worker_id)
        return payload

    def _on_stop(self, req: dict) -> dict:
        # Register the objects this worker still owns with the master before
        # exit? No — ownership semantics: non-transferred objects die with
        # the worker; the master unlinks them on WorkerStopped/death.
        self._stop_event.set()
        return {"stopping": True}

    def _serve_debug(self):
        """Per-worker /healthz + /debug endpoints when
        RAYDP_TPU_DEBUG_PORT is set (0 = ephemeral, logged). The wedged
        process answering 503 here while /metrics keeps serving is the
        per-process face of the health plane."""
        from raydp_tpu.telemetry import (
            DEBUG_PORT_ENV,
            render_prometheus,
            serve_prometheus,
        )

        port = os.environ.get(DEBUG_PORT_ENV)
        if port is None:
            return None
        try:
            return serve_prometheus(
                lambda: render_prometheus(
                    {"workers": {self.worker_id: metrics.snapshot()}}
                ),
                int(port),
            )
        except Exception:
            logger.exception("worker debug endpoint failed to start")
            return None

    def run(self) -> None:
        self.register()
        _flight.record("state", "registered", worker_id=self.worker_id)
        debug_server = self._serve_debug()
        missed = 0
        beat_index = 0
        while not self._stop_event.wait(2.0):
            # Fault-plan hook: hb_stall silences this worker's beats so
            # the master's liveness monitor sees a partitioned host.
            if _fault.active() and _fault.on_heartbeat(
                beat_index, worker=self.worker_id
            ):
                beat_index += 1
                continue
            beat_index += 1
            beat = {"worker_id": self.worker_id}
            # Refresh resource gauges (RSS, HBM, store occupancy) so the
            # delta below ships them to the master's merged view.
            try:
                from raydp_tpu.utils.profiling import sample_resource_gauges

                sample_resource_gauges()
            except Exception:
                pass
            delta = self._shipper.delta()
            if delta:
                beat["metrics"] = delta
            # Ship stall flags so the master's health_report() names
            # this worker and the stuck component while the task RPC is
            # still open (long before any heartbeat timeout: a wedged
            # task does not stop THIS thread).
            health = _watchdog.health()
            if not health.get("healthy", True):
                beat["health"] = {"stalls": health.get("stalls", {})}
            reply = self.master.try_call("Heartbeat", beat, timeout=8.0)
            # Shard spans continuously (no-op without a telemetry dir):
            # the driver's live trace_report() sees worker spans at
            # heartbeat latency, and a later SIGKILL loses ≤1 beat.
            flush_spans()
            with self._busy_lock:
                busy = self._busy > 0
            if reply is None:
                _flight.record("heartbeat", "missed", missed=missed + 1)
                # Failed beats must not eat their metrics delta: re-ship
                # the sections on the next beat.
                self._shipper.rollback(delta)
                # Transient master hiccups — including a driver process
                # saturated by a big shuffle on a small host — are
                # absorbed; only a sustained outage means exit. And never
                # while a task is executing: a starved master during a
                # shuffle is the NORM on small hosts, and exiting here
                # cancels the very task the driver is waiting on.
                missed += 1
                if missed >= 8 and not busy:
                    logger.warning(
                        "worker %s: master unreachable for %d beats; exiting",
                        self.worker_id, missed,
                    )
                    break
                if missed >= 60:
                    # Hard cap even while busy: with the driver truly
                    # gone AND the task wedged (user-code deadlock),
                    # nothing else can ever kill this process — without
                    # a bound it would orphan forever with its shm
                    # segments. 60 beats ≈ several minutes of sustained
                    # outage, far beyond any GIL stall.
                    logger.error(
                        "worker %s: master unreachable for %d beats with "
                        "a task still in flight; exiting to avoid an "
                        "immortal orphan", self.worker_id, missed,
                    )
                    break
                continue
            missed = 0
            if not reply.get("known", False):
                if busy:
                    # The master wrote us off (its monitor starved while
                    # our heartbeats queued) but the driver's task RPC to
                    # us is still open — finish it; the result makes it
                    # back on that same channel. Exit once idle.
                    logger.warning(
                        "worker %s: master disowned us mid-task; finishing "
                        "in-flight work before exiting", self.worker_id,
                    )
                    continue
                # Master explicitly wrote us off — exit now (parity with
                # executor exit on AppMaster disconnect).
                logger.warning("worker %s: master disowned us; exiting",
                               self.worker_id)
                break
        # Final snapshot, not a delta: a clean exit must leave the master's
        # tombstoned view complete even if the last few deltas were lost.
        self.master.try_call(
            "WorkerStopped",
            {"worker_id": self.worker_id, "metrics": self._shipper.full()},
            timeout=2.0,
        )
        _flight.record("state", "stopping", worker_id=self.worker_id)
        # Tail spans of a clean exit (the atexit hook is a backstop for
        # paths that bypass run(), e.g. a registration failure).
        flush_spans()
        if debug_server is not None:
            debug_server.close()
        with self._task_pool_lock:
            if self._task_pool is not None:
                self._task_pool.shutdown(wait=False)
        self._server.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--master", required=True)
    parser.add_argument("--node-id", default="node-0")
    parser.add_argument("--cores", type=float, default=1.0)
    parser.add_argument("--memory", type=float, default=0.0)
    parser.add_argument("--bind-host", default="127.0.0.1")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[{args.worker_id}] %(levelname)s %(message)s",
    )
    # Join the driver's job trace (RAYDP_TPU_TRACEPARENT in our launch
    # env) before any span is recorded; flush tail spans on interpreter
    # exit so clean shutdowns never lose the last buffer. The job
    # identity (RAYDP_TPU_JOB) is adopted the same way, so usage this
    # process emits outside any RPC scope still bills correctly.
    trace_prop.adopt_env_context()
    _acct.adopt_env_job()
    # Health plane: black box (crash/SIGTERM postmortem bundles),
    # trace-stamped JSONL logs, and the progress watchdog.
    _flight.install(component="worker")
    _logs.install()
    _watchdog.ensure_started()
    atexit.register(flush_spans)
    worker = Worker(
        args.worker_id,
        args.master,
        args.node_id,
        {"cpu": args.cores, "memory": args.memory},
        bind_host=args.bind_host,
    )
    try:
        worker.run()
    except Exception:
        traceback.print_exc()
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
