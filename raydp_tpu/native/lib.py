"""ctypes bindings for the native data-plane library, with numpy fallback.

``gather_matrix`` assembles a training minibatch — rows ``indices`` of the
given numeric columns — into a contiguous row-major array ready for
``jax.device_put``. The native path avoids numpy's per-column fancy-index +
stack (which materializes column-major intermediates) and parallelizes
across rows.
"""
from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

import numpy as np

from raydp_tpu.native import build

_COL_TYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int64): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.int16): 4,
    np.dtype(np.uint8): 5,
}

_lib = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("RAYDP_TPU_DISABLE_NATIVE") == "1":
        return None
    path = build.ensure_built()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.rdp_gather.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    for name in ("rdp_gather_matrix_f32", "rdp_gather_matrix_i32"):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
    lib.rdp_hash_bucket.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def gather_matrix(
    columns: Sequence[np.ndarray],
    indices: np.ndarray,
    out_dtype=np.float32,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """``out[i, c] = columns[c][indices[i]]`` cast to ``out_dtype``.

    Columns must be 1-D, contiguous, numeric. ``out_dtype`` must be
    float32 or int32 (the two infeed staging formats).
    """
    ncols = len(columns)
    if ncols == 0:
        raise ValueError("need at least one column")
    out_dtype = np.dtype(out_dtype)
    if out_dtype not in (np.dtype(np.float32), np.dtype(np.int32)):
        raise ValueError("out_dtype must be float32 or int32")
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    n = indices.shape[0]
    cols = [np.ascontiguousarray(c) for c in columns]
    n_src = min(c.shape[0] for c in cols)
    _check_indices(indices, n_src)
    if out is None:
        out = np.empty((n, ncols), dtype=out_dtype)
    else:
        if (
            out.shape != (n, ncols)
            or out.dtype != out_dtype
            or not out.flags.c_contiguous
        ):
            raise ValueError("out must be C-contiguous (n, ncols) of out_dtype")

    lib = _load()
    if lib is not None and all(c.dtype in _COL_TYPES for c in cols):
        col_ptrs = (ctypes.c_void_p * ncols)(
            *[c.ctypes.data_as(ctypes.c_void_p).value for c in cols]
        )
        col_types = np.array([_COL_TYPES[c.dtype] for c in cols], dtype=np.int32)
        fn = (
            lib.rdp_gather_matrix_f32
            if out_dtype == np.float32
            else lib.rdp_gather_matrix_i32
        )
        fn(
            col_ptrs,
            col_types.ctypes.data_as(ctypes.c_void_p),
            ncols,
            indices.ctypes.data_as(ctypes.c_void_p),
            n,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out

    # numpy fallback
    for c_idx, col in enumerate(cols):
        out[:, c_idx] = col[indices].astype(out_dtype, copy=False)
    return out


def _check_indices(indices: np.ndarray, n_src: int) -> None:
    """Native kernels do raw pointer math — validate here (the numpy
    fallback would raise IndexError; match that contract)."""
    if indices.size and (indices.min() < 0 or indices.max() >= n_src):
        raise IndexError(
            f"gather indices out of range [0, {n_src}) "
            f"(min={indices.min()}, max={indices.max()})"
        )


def hash_bucket(
    columns: Sequence[np.ndarray], n_buckets: int
) -> Optional[np.ndarray]:
    """Stable per-row bucket ids from numeric key columns (the shuffle
    partitioner hot path). Returns None when a column dtype is
    unsupported — callers fall back to the pandas hash.

    CONSISTENCY CONTRACT: every partition of one exchange must assign
    equal keys to equal buckets, and partitions are hashed in different
    processes. Therefore the RESULT depends only on the values: when the
    native library is unavailable, an exact numpy twin of the splitmix64
    kernel computes the identical buckets (never a different algorithm).
    """
    if not columns:
        return None
    cols = []
    for c in columns:
        c = np.ascontiguousarray(c)
        if c.dtype not in _COL_TYPES or c.ndim != 1:
            return None
        cols.append(c)
    n = cols[0].shape[0]
    if any(c.shape[0] != n for c in cols):
        return None
    lib = _load()
    if lib is None:
        return _hash_bucket_numpy(cols, n_buckets)
    out = np.empty(n, dtype=np.int64)
    col_ptrs = (ctypes.c_void_p * len(cols))(
        *[c.ctypes.data_as(ctypes.c_void_p).value for c in cols]
    )
    col_types = np.array([_COL_TYPES[c.dtype] for c in cols], dtype=np.int32)
    lib.rdp_hash_bucket(
        col_ptrs,
        col_types.ctypes.data_as(ctypes.c_void_p),
        len(cols),
        n,
        int(n_buckets),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized twin of the C++ rdp_mix64 (bit-exact)."""
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _load_bits_np(c: np.ndarray) -> np.ndarray:
    """Twin of the C++ load_bits: the uint64 the kernel hashes."""
    if c.dtype == np.float32:
        c = np.where(c == 0.0, np.float32(0.0), c)  # -0.0 → +0.0
        return c.view(np.uint32).astype(np.uint64)
    if c.dtype == np.float64:
        c = np.where(c == 0.0, 0.0, c)
        return c.view(np.uint64)
    if c.dtype == np.uint8:
        return c.astype(np.uint64)
    # signed ints: sign-extend exactly like the C++ int64_t cast
    return c.astype(np.int64).view(np.uint64)


def _hash_bucket_numpy(cols, n_buckets: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = np.full(cols[0].shape[0], 0x517CC1B727220A95, dtype=np.uint64)
        for i, c in enumerate(cols):
            v = _load_bits_np(c) + np.uint64(
                (0x100000001B3 * i) & 0xFFFFFFFFFFFFFFFF
            )
            h = _splitmix64_np(h ^ _splitmix64_np(v))
        return (h % np.uint64(n_buckets)).astype(np.int64)


def gather_rows(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Row gather on a 2-D contiguous array via the native kernel."""
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    if src.ndim != 2:
        raise ValueError("gather_rows expects a 2-D array")
    _check_indices(indices, src.shape[0])
    lib = _load()
    if lib is None or not src.flags.c_contiguous:
        return src[indices]
    n = indices.shape[0]
    out = np.empty((n, src.shape[1]), dtype=src.dtype)
    width = src.strides[0]
    lib.rdp_gather(
        src.ctypes.data_as(ctypes.c_void_p),
        indices.ctypes.data_as(ctypes.c_void_p),
        n,
        width,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out
