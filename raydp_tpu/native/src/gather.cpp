// Native data-plane kernels for raydp_tpu.
//
// Role parity with the reference's out-of-Python data plane (reference:
// core/.../sql/raydp/ObjectStoreWriter.scala:93-144 — the per-row Arrow
// write loop runs in JVM executors). Here the host-side hot loop is the
// inverse: assembling shuffled training minibatches from Arrow column
// buffers into a contiguous row-major staging buffer that jax.device_put
// ships to HBM. Python/numpy does this at ~1 GB/s with fancy indexing and
// a transpose; this does it cache-friendly and multithreaded.
//
// Built with: g++ -O3 -march=native -fopenmp -shared -fPIC
// Exposed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>

extern "C" {

// Column element types for mixed-dtype matrix assembly.
enum ColType : int32_t {
  COL_F32 = 0,
  COL_F64 = 1,
  COL_I64 = 2,
  COL_I32 = 3,
  COL_I16 = 4,
  COL_U8 = 5,
};

// Gather fixed-width rows: dst[i] = src[idx[i]], element width `width` bytes.
void rdp_gather(const uint8_t* src, const int64_t* idx, int64_t n,
                int64_t width, uint8_t* dst) {
  switch (width) {
    case 4: {
      const uint32_t* s = reinterpret_cast<const uint32_t*>(src);
      uint32_t* d = reinterpret_cast<uint32_t*>(dst);
#pragma omp parallel for if (n > 65536)
      for (int64_t i = 0; i < n; ++i) d[i] = s[idx[i]];
      return;
    }
    case 8: {
      const uint64_t* s = reinterpret_cast<const uint64_t*>(src);
      uint64_t* d = reinterpret_cast<uint64_t*>(dst);
#pragma omp parallel for if (n > 65536)
      for (int64_t i = 0; i < n; ++i) d[i] = s[idx[i]];
      return;
    }
    default: {
#pragma omp parallel for if (n * width > 1 << 19)
      for (int64_t i = 0; i < n; ++i)
        std::memcpy(dst + i * width, src + idx[i] * width, width);
    }
  }
}

static inline float load_as_f32(const void* col, int32_t type, int64_t row) {
  switch (type) {
    case COL_F32:
      return reinterpret_cast<const float*>(col)[row];
    case COL_F64:
      return static_cast<float>(reinterpret_cast<const double*>(col)[row]);
    case COL_I64:
      return static_cast<float>(reinterpret_cast<const int64_t*>(col)[row]);
    case COL_I32:
      return static_cast<float>(reinterpret_cast<const int32_t*>(col)[row]);
    case COL_I16:
      return static_cast<float>(reinterpret_cast<const int16_t*>(col)[row]);
    case COL_U8:
      return static_cast<float>(reinterpret_cast<const uint8_t*>(col)[row]);
    default:
      return 0.0f;
  }
}

// Assemble dst[n, ncols] float32 row-major from ncols typed column buffers,
// taking rows idx[0..n). The feature-matrix hot path of the training infeed.
void rdp_gather_matrix_f32(const void** cols, const int32_t* col_types,
                           int64_t ncols, const int64_t* idx, int64_t n,
                           float* dst) {
#pragma omp parallel for if (n * ncols > 1 << 16)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t row = idx[i];
    float* out = dst + i * ncols;
    for (int64_t c = 0; c < ncols; ++c) {
      out[c] = load_as_f32(cols[c], col_types[c], row);
    }
  }
}

// Same, but into int32 (label/categorical path).
void rdp_gather_matrix_i32(const void** cols, const int32_t* col_types,
                           int64_t ncols, const int64_t* idx, int64_t n,
                           int32_t* dst) {
#pragma omp parallel for if (n * ncols > 1 << 16)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t row = idx[i];
    int32_t* out = dst + i * ncols;
    for (int64_t c = 0; c < ncols; ++c) {
      switch (col_types[c]) {
        case COL_I64:
          out[c] = static_cast<int32_t>(
              reinterpret_cast<const int64_t*>(cols[c])[row]);
          break;
        case COL_I32:
          out[c] = reinterpret_cast<const int32_t*>(cols[c])[row];
          break;
        case COL_I16:
          out[c] = reinterpret_cast<const int16_t*>(cols[c])[row];
          break;
        case COL_U8:
          out[c] = reinterpret_cast<const uint8_t*>(cols[c])[row];
          break;
        case COL_F32:
          out[c] = static_cast<int32_t>(
              reinterpret_cast<const float*>(cols[c])[row]);
          break;
        case COL_F64:
          out[c] = static_cast<int32_t>(
              reinterpret_cast<const double*>(cols[c])[row]);
          break;
        default:
          out[c] = 0;
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Hash partitioner: the shuffle hot path. Computes a stable bucket id per
// row from numeric key columns (splitmix64 mixing, order-sensitive across
// columns). Must be deterministic across processes — every partition of an
// exchange computes buckets independently and equal keys must collide.

static inline uint64_t rdp_mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

static inline uint64_t load_bits(const void* col, int32_t type, int64_t row) {
  switch (type) {
    case COL_F32: {
      float v = reinterpret_cast<const float*>(col)[row];
      if (v == 0.0f) v = 0.0f;  // -0.0 → +0.0
      uint32_t b;
      std::memcpy(&b, &v, 4);
      return b;
    }
    case COL_F64: {
      double v = reinterpret_cast<const double*>(col)[row];
      if (v == 0.0) v = 0.0;
      uint64_t b;
      std::memcpy(&b, &v, 8);
      return b;
    }
    case COL_I64:
      return static_cast<uint64_t>(
          reinterpret_cast<const int64_t*>(col)[row]);
    case COL_I32:
      return static_cast<uint64_t>(static_cast<int64_t>(
          reinterpret_cast<const int32_t*>(col)[row]));
    case COL_I16:
      return static_cast<uint64_t>(static_cast<int64_t>(
          reinterpret_cast<const int16_t*>(col)[row]));
    case COL_U8:
      return reinterpret_cast<const uint8_t*>(col)[row];
    default:
      return 0;
  }
}

extern "C" void rdp_hash_bucket(const void** cols, const int32_t* col_types,
                                int64_t ncols, int64_t n, int64_t n_buckets,
                                int64_t* out) {
#pragma omp parallel for if (n > 16384)
  for (int64_t i = 0; i < n; ++i) {
    uint64_t h = 0x51'7c'c1'b7'27'22'0a'95ULL;
    for (int64_t c = 0; c < ncols; ++c) {
      h = rdp_mix64(h ^ rdp_mix64(load_bits(cols[c], col_types[c], i) +
                                  0x100000001b3ULL * (uint64_t)c));
    }
    out[i] = static_cast<int64_t>(h % static_cast<uint64_t>(n_buckets));
  }
}
