"""Lazy build of the native data-plane library.

Compiles raydp_tpu/native/src/*.cpp into libraydp_native.so with g++ the
first time it's needed (or when sources are newer than the .so). No
pybind11 in this image — the library is plain ``extern "C"`` + ctypes.
"""
from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_HERE, "src")
_LIB_PATH = os.path.join(_HERE, "libraydp_native.so")
_lock = threading.Lock()


def lib_path() -> str:
    return _LIB_PATH


def _sources() -> list:
    if not os.path.isdir(_SRC_DIR):
        return []
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp")
    )


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in _sources())


def ensure_built(verbose: bool = False) -> Optional[str]:
    """Build if needed; returns the .so path, or None if no toolchain."""
    with _lock:
        if not _stale():
            return _LIB_PATH
        srcs = _sources()
        if not srcs:  # sources not shipped (e.g. wheel install) → fallback
            return None
        # Build to a process-private temp path, then atomically rename:
        # concurrent worker processes may race here, and a peer must never
        # dlopen a half-written .so.
        tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
        flag_sets = [
            ["-O3", "-march=native", "-fopenmp"],
            ["-O3"],  # -march=native / openmp may be unsupported
        ]
        try:
            for flags in flag_sets:
                cmd = ["g++", *flags, "-shared", "-fPIC", "-o", tmp, *srcs]
                try:
                    # raydp: ignore[R1] — the lock intentionally covers
                    # the compile so concurrent callers build exactly
                    # once; callers tolerate the (bounded) wait.
                    subprocess.run(
                        cmd,
                        check=True,
                        capture_output=not verbose,
                        timeout=120,
                    )
                except (subprocess.SubprocessError, FileNotFoundError):
                    continue
                os.replace(tmp, _LIB_PATH)
                return _LIB_PATH
            return None
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
