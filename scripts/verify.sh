#!/usr/bin/env bash
# Tier-1 verification gate (see ROADMAP.md): the fast, CPU-only test
# suite every change must keep green. Runs from any cwd.
#
#   scripts/verify.sh [extra pytest args]
#
# Prints DOTS_PASSED=<n> (count of progress dots = passing tests) and
# exits with pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."

LOG="${T1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
# Crash black box for CI: every test-spawned process dumps a postmortem
# bundle here on crash/SIGTERM/watchdog stall; shipped on failure below.
export RAYDP_TPU_POSTMORTEM_DIR="${RAYDP_TPU_POSTMORTEM_DIR:-/tmp/raydp_tpu_postmortem.$$}"
# Query-profiling artifacts: every DataFrame stage the tests execute
# appends its StageStats record here (stats-<pid>.jsonl shards),
# dumped below on failure so CI shows what the engine was doing.
export RAYDP_TPU_STATS_DIR="${RAYDP_TPU_STATS_DIR:-/tmp/raydp_tpu_stats.$$}"
# Machine-readable smoke-gate metrics (preempt MTTR, serve fill,
# time-to-grow, SLO breach-detect/MTTR): each gate below stamps its
# numbers here via scripts/verify_metrics.py; the advisory step at the
# bottom diffs them against the previous run's stamp with the same
# bench_compare rules that gate the BENCH leaves.
export VERIFY_METRICS_PATH="${VERIFY_METRICS_PATH:-$PWD/VERIFY_METRICS.json}"
if [ -f "$VERIFY_METRICS_PATH" ]; then
  mv -f "$VERIFY_METRICS_PATH" "${VERIFY_METRICS_PATH%.json}.prev.json"
fi
# On any gate failure, ship the unified dashboard with the black box:
# the same document /debug/dashboard serves, rebuilt offline from the
# gate's telemetry dir (or the local registry when the gate kept none).
dump_dashboard() {
  echo "--- dashboard dump (postmortem) ---"
  JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.dashboard --json "$@" \
    || echo "(dashboard unavailable)"
}
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
if [ "$rc" -ne 0 ]; then
  # Ship the black box with the failure: newest bundle's reason + last
  # flight events (no-op message when nothing crashed).
  echo "--- newest postmortem bundle (if any) ---"
  python -m raydp_tpu.telemetry.flight_recorder "$RAYDP_TPU_POSTMORTEM_DIR" || true
  # Stage-stats tail + live progress: which stages ran last, and what
  # (if anything) was still in flight when the suite died.
  echo "--- last dataframe stage stats (if any) ---"
  newest_shard=$(ls -t "$RAYDP_TPU_STATS_DIR"/stats-*.jsonl 2>/dev/null | head -1)
  if [ -n "$newest_shard" ]; then
    tail -5 "$newest_shard"
  else
    echo "(no stage-stat shards)"
  fi
  echo "--- progress report ---"
  JAX_PLATFORMS=cpu python -c 'import json; from raydp_tpu.telemetry.progress import progress; print(json.dumps(progress.report()))' || true
fi
# Static analysis gate (HARD): raydpcheck must report zero
# non-baselined findings over raydp_tpu/ (rules R1-R5, doc/analysis.md).
# Budget <30s — it runs in ~2s; the JSON report ships on failure like
# the other black boxes above.
if [ "$rc" -eq 0 ]; then
  echo "--- static analysis (raydpcheck) ---"
  check_json="/tmp/raydpcheck.$$.json"
  if timeout -k 5 30 python -m raydp_tpu.analysis raydp_tpu/ \
      --json-out "$check_json"; then
    echo "RAYDPCHECK=ok"
  else
    echo "RAYDPCHECK=failed"
    echo "--- raydpcheck JSON report ---"
    cat "$check_json" 2>/dev/null || echo "(no report written)"
    dump_dashboard
    rc=1
  fi
  rm -f "$check_json"
fi
# EXPLAIN ANALYZE smoke: a window->groupBy pipeline must profile end to
# end and the analyze CLI must fold its stats shards into the report.
if [ "$rc" -eq 0 ]; then
  echo "--- explain-analyze smoke ---"
  smoke_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_STATS_DIR="$smoke_dir" python - <<'PYEOF' \
    && JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.analyze "$smoke_dir" >/dev/null \
    && echo "ANALYZE_SMOKE=ok" \
    || { echo "ANALYZE_SMOKE=failed"; dump_dashboard; rc=1; }
import numpy as np, pandas as pd
import raydp_tpu.dataframe as rdf
from raydp_tpu.dataframe import dataframe as D
D._EXCHANGE_COALESCE_BYTES = 0
df = rdf.from_pandas(
    pd.DataFrame({"k": np.arange(4000) % 13, "v": np.arange(4000.0)}),
    num_partitions=4,
)
out = df.withColumn(
    "rn", rdf.row_number().over(rdf.Window.partitionBy("k").orderBy("v"))
).groupBy("k").agg({"v": "max"})
text = out.explain(analyze=True, quiet=True)
assert "== Physical Plan ==" in text and "skew" in text, text
PYEOF
  rm -rf "$smoke_dir"
fi
# AQE smoke (HARD): a parquet-scan -> zipfian groupBy pipeline on a
# 2-worker cluster must replan at runtime — the scan rule pushes the
# projection + predicate into the executor-side parquet read (pruning
# whole files from footer stats) and the coalesce rule merges the
# small post-shuffle buckets the skewed keys leave behind — with every
# decision visible in explain(analyze=True), and the adaptive plan
# must beat the static planner (RAYDP_TPU_AQE=0) on wall clock,
# best-of-3 interleaved. The speedup is stamped into VERIFY_METRICS so
# the drift check below catches regressions in the replan rules
# themselves. doc/performance.md "Adaptive query engine" is the story
# this gate proves end to end.
if [ "$rc" -eq 0 ]; then
  echo "--- aqe smoke (runtime replanning A/B) ---"
  aqe_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu AQE_SMOKE_DIR="$aqe_dir" python - <<'PYEOF' \
    && echo "AQE_SMOKE=ok" \
    || { echo "AQE_SMOKE=failed"; dump_dashboard; rc=1; }
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# Keep the replan floor below this smoke's data volume; everything
# else runs at the documented defaults.
os.environ["RAYDP_TPU_AQE_MIN_EXCHANGE_MB"] = "0.05"

import raydp_tpu
import raydp_tpu.dataframe as rdf
from raydp_tpu.dataframe import aqe as _aqe
from raydp_tpu.dataframe import col
from raydp_tpu.dataframe import dataframe as D

# Force real exchanges: the coalesced-gather shortcut would swallow
# the exchange before the replan hook ever measured a bucket.
D._EXCHANGE_COALESCE_BYTES = 0
D._AGG_COALESCE_BYTES = 0
D._COMBINE_COALESCE_BYTES = 0

raydp_tpu.init(app_name="aqe-smoke", num_workers=2,
               memory_per_worker="512MB")

data_dir = os.environ["AQE_SMOKE_DIR"]
rng = np.random.RandomState(7)
rows_per_file, n_files = 25_000, 16
for i in range(n_files):
    n = rows_per_file
    t = pa.table({
        "k": np.minimum(rng.zipf(1.3, n), 100_000).astype(np.int64),
        "v": rng.rand(n),
        "ts": np.arange(i * n, (i + 1) * n, dtype=np.int64),
        **{f"b{j}": rng.rand(n) for j in range(5)},
    })
    pq.write_table(t, f"{data_dir}/part-{i:02d}.parquet")


def run(aqe):
    os.environ["RAYDP_TPU_AQE"] = aqe
    t0 = time.monotonic()
    out = (rdf.read_parquet(data_dir)
           .filter(col("ts") < 200_000)
           .select("k", "v")
           .groupBy("k").agg({"v": "sum"}))
    nrows = out.count()
    return time.monotonic() - t0, nrows, out


run("1")  # warm both arms before timing
run("0")
times = {"0": [], "1": []}
rows = set()
for _ in range(3):
    for arm in ("1", "0"):
        dt, nrows, out = run(arm)
        times[arm].append(dt)
        rows.add(nrows)
assert len(rows) == 1, f"adaptive plan changed the result: {rows}"

_, nrows, out = run("1")
text = out.explain(analyze=True, quiet=True)
marks = _aqe.rule_counts(text)
assert marks.get("scan"), f"no scan replan in plan:\n{text}"
assert marks.get("coalesce"), f"no coalesce replan in plan:\n{text}"

best_static, best_aqe = min(times["0"]), min(times["1"])
speedup = best_static / best_aqe
assert speedup > 1.05, (
    f"adaptive plan did not beat static: {best_aqe:.3f}s vs "
    f"{best_static:.3f}s (speedup {speedup:.3f})"
)
print(f"AQE speedup {speedup:.2f}x "
      f"({best_aqe:.3f}s adaptive vs {best_static:.3f}s static), "
      f"replans {marks}")

exec(open("scripts/verify_metrics.py").read())
stamp("aqe_smoke", {
    "aqe_speedup": round(speedup, 3),
    "aqe_rows_per_sec": rows_per_file * n_files / best_aqe,
})
raydp_tpu.stop()
PYEOF
  rm -rf "$aqe_dir"
fi
# Chaos smoke (HARD): a tiny supervised fit with an injected rank kill
# must auto-recover (exactly one restart, resume from the mid-step
# checkpoint) and land on the SAME loss as an uninterrupted run —
# the end-to-end proof that doc/fault_tolerance.md's recovery story
# holds, not just its unit tests.
if [ "$rc" -eq 0 ]; then
  echo "--- chaos smoke (injected rank kill) ---"
  JAX_PLATFORMS=cpu python - <<'PYEOF' \
    && echo "CHAOS_SMOKE=ok" \
    || { echo "CHAOS_SMOKE=failed"; dump_dashboard; rc=1; }
import os
import tempfile

import numpy as np
import pandas as pd

import raydp_tpu.dataframe as rdf
from raydp_tpu.data import MLDataset
from raydp_tpu.train.spmd_fit import fit_spmd


def factory_builder(ckpt):
    def make_estimator():
        import jax
        import optax

        from raydp_tpu.models import MLP
        from raydp_tpu.parallel import MeshSpec
        from raydp_tpu.train import JAXEstimator

        return JAXEstimator(
            model=MLP(hidden=(8,), out_dim=1), optimizer=optax.adam(3e-2),
            loss="mse", num_epochs=2, batch_size=128,
            feature_columns=["a", "b"], label_column="y",
            mesh=MeshSpec(dp=len(jax.devices())), seed=0, shuffle=False,
            epoch_mode="stream", checkpoint_dir=ckpt, save_every_steps=2,
        )

    return make_estimator


rng = np.random.default_rng(0)
a, b = rng.standard_normal(512), rng.standard_normal(512)
pdf = pd.DataFrame({"a": a, "b": b, "y": 2 * a - 3 * b + 1})
ds = MLDataset.from_df(rdf.from_pandas(pdf, num_partitions=2), num_shards=1)
root = tempfile.mkdtemp()
clean = fit_spmd(
    factory_builder(os.path.join(root, "clean")), ds, world_size=1,
    env={"JAX_PLATFORMS": "cpu"}, timeout=300,
)
chaos_ck = os.path.join(root, "chaos")
chaos = fit_spmd(
    factory_builder(chaos_ck), ds, world_size=1,
    env={
        "JAX_PLATFORMS": "cpu",
        "RAYDP_TPU_FAULT_PLAN": "kill:rank=0,step=2",
    },
    timeout=300, checkpoint_dir=chaos_ck,
)
assert chaos["restarts"] == 1, f"expected 1 restart, got {chaos['restarts']}"
assert os.path.isdir(os.path.join(chaos_ck, "step_mid_2")), "no mid ckpt"
np.testing.assert_allclose(
    chaos["history"][-1]["train_loss"],
    clean["history"][-1]["train_loss"], rtol=1e-4,
)
PYEOF
fi
# Job accounting smoke (HARD): two jobs running concurrently on one
# driver must produce disjoint per-job usage (chip-seconds from their
# fits, shuffle bytes from their exchanges) whose sums equal the
# cluster-global totals, and the event-timeline CLI must render a
# non-empty per-job timeline from the same run's shards — the
# end-to-end proof of doc/telemetry.md's "Job accounting & event
# timeline" story.
if [ "$rc" -eq 0 ]; then
  echo "--- job accounting smoke (2 concurrent jobs) ---"
  acct_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_TELEMETRY_DIR="$acct_dir" python - <<'PYEOF' \
    && JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.events "$acct_dir" \
         | grep -q "== job" \
    && echo "ACCOUNTING_SMOKE=ok" \
    || { echo "ACCOUNTING_SMOKE=failed"; dump_dashboard "$acct_dir"; rc=1; }
import threading
import time

import numpy as np
import pandas as pd

import raydp_tpu.dataframe as rdf
from raydp_tpu import telemetry
from raydp_tpu.dataframe import dataframe as D
from raydp_tpu.utils.profiling import metrics

_t0 = time.monotonic()

# Force real exchanges: coalesced groupBys move no bytes to attribute.
D._EXCHANGE_COALESCE_BYTES = 0
D._AGG_COALESCE_BYTES = 0
D._COMBINE_COALESCE_BYTES = 0


def workload(job, seed):
    rs = np.random.RandomState(seed)
    pdf = pd.DataFrame(
        {"k": rs.randint(0, 64, 20_000), "v": rs.rand(20_000)}
    )
    with telemetry.job_scope(job):
        rdf.from_pandas(pdf, num_partitions=4) \
            .groupBy("k").agg({"v": "sum"}).to_pandas()
        from raydp_tpu.models.mlp import MLP
        from raydp_tpu.train.estimator import JAXEstimator

        x = rs.rand(256, 2).astype(np.float32)
        tdf = pd.DataFrame(x, columns=["f0", "f1"])
        tdf["label"] = x.sum(axis=1)
        JAXEstimator(
            model=MLP(hidden=(4,), out_dim=1), loss="mse",
            num_epochs=1, batch_size=64,
            feature_columns=["f0", "f1"], label_column="label",
        ).fit_on_df(tdf)


jobs = [telemetry.mint_job("smoke-a"), telemetry.mint_job("smoke-b")]
threads = [
    threading.Thread(target=workload, args=(j, i))
    for i, j in enumerate(jobs)
]
for t in threads:
    t.start()
for t in threads:
    t.join()

report = telemetry.usage_report({"driver": metrics.snapshot()})
assert jobs[0].job_id != jobs[1].job_id
for j in jobs:
    usage = report["jobs"][j.job_id]["usage"]
    assert usage.get("shuffle_bytes", 0) > 0, (j.job_id, usage)
    assert usage.get("chip_seconds", 0) > 0, (j.job_id, usage)
for kind in ("shuffle_bytes", "chip_seconds"):
    total = report["totals"][kind]
    per_job = sum(
        r["usage"].get(kind, 0.0) for r in report["jobs"].values()
    )
    assert abs(total - per_job) <= 1e-6 * max(1.0, total), \
        (kind, total, per_job)

elapsed = time.monotonic() - _t0
exec(open("scripts/verify_metrics.py").read())
stamp("accounting_smoke", {
    "shuffle_bytes_per_sec": report["totals"]["shuffle_bytes"] / elapsed,
    "chip_seconds": report["totals"]["chip_seconds"],
})
PYEOF
  rm -rf "$acct_dir"
fi
# Scheduler smoke (HARD): with the arbiter enabled (capacity 1), a
# high-priority arrival must preempt the running low-priority gang,
# which drains to a step_emergency_* checkpoint and releases its
# slot; the arrival completes untouched, the victim auto-resumes and
# lands on the SAME loss as an unpreempted run (exact-position resume
# — replay bounded by one save_every_steps interval), and the
# event-timeline CLI renders the preempt->resume MTTR episode — the
# end-to-end proof of doc/scheduling.md's preemption story.
if [ "$rc" -eq 0 ]; then
  echo "--- scheduler smoke (priority preemption) ---"
  sched_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_TELEMETRY_DIR="$sched_dir" python - <<'PYEOF' \
    && JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.events "$sched_dir" \
         | grep -q "sched/preempt -> sched/resume" \
    && echo "SCHED_SMOKE=ok" \
    || { echo "SCHED_SMOKE=failed"; dump_dashboard "$sched_dir"; rc=1; }
import glob
import os
import tempfile
import threading
import time

import numpy as np
import pandas as pd

import raydp_tpu.dataframe as rdf
from raydp_tpu import control, telemetry
from raydp_tpu.data import MLDataset
from raydp_tpu.train.spmd_fit import fit_spmd


def factory_builder(ckpt, num_epochs, save_every=0):
    def make_estimator():
        import jax
        import optax

        from raydp_tpu.models import MLP
        from raydp_tpu.parallel import MeshSpec
        from raydp_tpu.train import JAXEstimator

        return JAXEstimator(
            model=MLP(hidden=(16,), out_dim=1), optimizer=optax.adam(3e-2),
            loss="mse", num_epochs=num_epochs, batch_size=128,
            feature_columns=["a", "b"], label_column="y",
            mesh=MeshSpec(dp=len(jax.devices())), seed=0, shuffle=False,
            epoch_mode="stream", checkpoint_dir=ckpt,
            save_every_steps=save_every,
        )

    return make_estimator


def dataset(n):
    rng = np.random.default_rng(0)
    a, b = rng.standard_normal(n), rng.standard_normal(n)
    pdf = pd.DataFrame({"a": a, "b": b, "y": 2 * a - 3 * b + 1})
    return MLDataset.from_df(rdf.from_pandas(pdf, num_partitions=2),
                             num_shards=1)


ds = dataset(4096)
arrival_ds = dataset(512)  # materialized up front: no ETL in the race
# Retention off for the victim so the emergency ckpt survives to the
# end of the (checkpoint-heavy) run for the glob assert below.
env = {"JAX_PLATFORMS": "cpu", "RAYDP_TPU_CKPT_KEEP": "0"}
root = tempfile.mkdtemp()
clean = fit_spmd(
    factory_builder(os.path.join(root, "clean"), 8, save_every=2), ds,
    world_size=1, env=env, timeout=300,
)

control.configure(capacity=1, admit_timeout_s=240.0)
victim_dir = os.path.join(root, "victim")
victim_out = {}


def run_victim():
    with telemetry.job_scope(telemetry.mint_job("victim", priority=0)):
        victim_out["res"] = fit_spmd(
            factory_builder(victim_dir, 8, save_every=2), ds,
            world_size=1, env=env, timeout=300, checkpoint_dir=victim_dir,
        )


vt = threading.Thread(target=run_victim, daemon=True)
vt.start()
# Arrival goes in only once the victim is visibly mid-epoch (first
# periodic checkpoint committed): the preemption must exercise the
# drain, not a startup race.
deadline = time.monotonic() + 240.0
mid = os.path.join(victim_dir, "step_mid_2", "_METADATA")
while time.monotonic() < deadline and not os.path.isfile(mid):
    time.sleep(0.05)
assert os.path.isfile(mid), "victim never reached its first mid ckpt"

_t_arr = time.monotonic()
with telemetry.job_scope(telemetry.mint_job("arrival", priority=5)):
    arrival = fit_spmd(
        factory_builder(None, 1), arrival_ds, world_size=1,
        env={"JAX_PLATFORMS": "cpu"}, timeout=300,
    )
arrival_elapsed = time.monotonic() - _t_arr
vt.join(300.0)
victim = victim_out["res"]

assert arrival["restarts"] == 0, arrival["restarts"]
assert victim["restarts"] == 1, victim["restarts"]
assert glob.glob(os.path.join(victim_dir, "step_emergency_*")), \
    "preemption did not drain an emergency checkpoint"
np.testing.assert_allclose(
    victim["history"][-1]["train_loss"],
    clean["history"][-1]["train_loss"], rtol=1e-4,
)

# Stamp the preempt->resume MTTR the timeline CLI renders below (the
# episode lives in the training subprocess's event shards).
from raydp_tpu.telemetry import events as events_mod

records = events_mod.load_event_records(os.environ["RAYDP_TPU_TELEMETRY_DIR"])
mttrs = [
    ep["repair_s"]
    for job in events_mod.mttr_report(records).values()
    for ep in job.get("episodes", [])
    if ep.get("start_kind") == "sched/preempt"
    and ep.get("end_kind") == "sched/resume"
]
exec(open("scripts/verify_metrics.py").read())
stamp("sched_smoke", {
    "preempt_mttr_s": max(mttrs) if mttrs else -1.0,
    "arrival_epochs_per_sec": len(arrival["history"]) / arrival_elapsed,
})
PYEOF
  rm -rf "$sched_dir"
fi
# Serving smoke (HARD): a replica group under concurrent traffic with
# an injected replica kill must reply to every accepted request
# exactly once (zero drops), keep batches usefully full, and self-heal
# back to full strength — the end-to-end proof of doc/serving.md's
# zero-dropped-request failover story, not just its unit tests.
if [ "$rc" -eq 0 ]; then
  echo "--- serving smoke (replica kill under traffic) ---"
  JAX_PLATFORMS=cpu RAYDP_TPU_FAULT_PLAN="serve_kill:replica=0,request=5" \
    python - <<'PYEOF' \
    && echo "SERVE_SMOKE=ok" \
    || { echo "SERVE_SMOKE=failed"; dump_dashboard; rc=1; }
import threading
import time

from raydp_tpu.serve import ReplicaGroup
from raydp_tpu.utils.profiling import metrics


def make_model():
    # Nested so cloudpickle ships it by value to the replica procs.
    def model(payloads, bucket):
        time.sleep(0.002)
        return [float(sum(p)) for p in payloads]

    return model


N, PER = 240, 30
results = [None] * N
errors = []
_t0 = time.monotonic()

with ReplicaGroup(
    replicas=2, model_fn=make_model(), label="smoke-serve",
    max_batch=4, slo_ms=25, max_queue=N + 16, restart_backoff_s=0.2,
).start() as group:

    def client(base):
        reqs = [
            (i, group.submit([i % 5] * 8, timeout_s=120.0))
            for i in range(base, base + PER)
        ]
        for i, req in reqs:
            try:
                results[i] = req.wait(timeout=120.0)
            except Exception as exc:  # any drop/cancel fails the gate
                errors.append((i, repr(exc)))

    threads = [
        threading.Thread(target=client, args=(b,))
        for b in range(0, N, PER)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Self-heal: the killed lineage must respawn back to full strength.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        stats = group.stats()
        if stats["restarts"] >= 1 and stats["replicas_alive"] == 2:
            break
        time.sleep(0.1)

assert not errors, errors[:3]
assert results == [float((i % 5) * 8) for i in range(N)], \
    "replies diverged"
assert stats["restarts"] >= 1, stats
assert stats["replicas_alive"] == 2, stats
assert stats["replies"] == N and stats["errors"] == 0, stats
snap = metrics.snapshot()["counters"]
fill = snap["serve/batch_requests"] / (snap["serve/batches"] * 4)
assert fill > 0.5, (fill, snap)

exec(open("scripts/verify_metrics.py").read())
stamp("serve_smoke", {
    "replies_per_sec": N / (time.monotonic() - _t0),
    "batch_fill": fill,
    "restarts": stats["restarts"],
})
PYEOF
fi
# Decode smoke (HARD): a decode-mode replica group streaming causal-LM
# tokens under concurrent traffic. Three acts against ONE live group:
# a serve_kill lands mid-decode and every in-flight sequence must
# finish token-identical to the in-process reference (the requeue-as-
# prefill recipe, zero drops); then, warm, the same prompts run
# batched vs one-request-at-a-time and continuous batching must clear
# 3x the sequential tokens/s — the end-to-end proof of
# doc/serving.md's iteration-level scheduling story.
if [ "$rc" -eq 0 ]; then
  echo "--- decode smoke (continuous batching + replica kill mid-decode) ---"
  JAX_PLATFORMS=cpu RAYDP_TPU_FAULT_PLAN="serve_kill:replica=0,request=4" \
    python - <<'PYEOF' \
    && echo "DECODE_SMOKE=ok" \
    || { echo "DECODE_SMOKE=failed"; dump_dashboard; rc=1; }
import time

from raydp_tpu.serve import ReplicaGroup
from raydp_tpu.serve.decode import build_transformer_engine
from raydp_tpu.utils.profiling import metrics

# Same factory the replica rebuilds from (seed-pinned init), so the
# driver-side reference decodes with byte-identical weights.
reference = build_transformer_engine(seed=0)

with ReplicaGroup(
    replicas=1, model_fn=build_transformer_engine, label="smoke-decode",
    mode="decode", restart_backoff_s=0.2, max_restarts=3,
    max_queue=64,
).start() as group:
    # Act 1 — kill mid-decode. The fault clause trips on the FIFTH
    # admission (request=4): the first wave of four is already
    # streaming when the trigger lands, so the driver must requeue
    # four live sequences as prefills of their generated-so-far
    # context onto the respawned replica.
    wave = [[i + 1, i + 2, i + 3] for i in range(4)]
    reqs = [group.submit_generate(p, max_new=48, timeout_s=240.0)
            for p in wave]
    deadline = time.monotonic() + 180.0
    while time.monotonic() < deadline:
        if metrics.snapshot()["counters"].get("decode/tokens", 0) >= 4:
            break
        time.sleep(0.01)
    trigger = group.submit_generate([9, 9], max_new=4, timeout_s=240.0)
    for p, r in zip(wave, reqs):
        assert r.wait(timeout=240.0)["tokens"] == \
            reference.reference_decode(p, 48), f"stream diverged for {p}"
    assert trigger.wait(timeout=240.0)["tokens"] == \
        reference.reference_decode([9, 9], 4), "trigger stream diverged"
    mid = group.stats()
    assert mid["restarts"] >= 1, mid
    assert mid["decode"]["requeued_prefills"] >= 1, mid

    # Self-heal before timing: the killed lineage back at strength.
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if group.stats()["replicas_alive"] == 1:
            break
        time.sleep(0.1)
    assert group.stats()["replicas_alive"] == 1, group.stats()

    # Act 2 — batched: 16 concurrent streams over 8 KV slots (the
    # second eight join mid-stream as the first wave retires). The
    # respawned replica is warm by now, so this times scheduling, not
    # XLA.
    prompts = [[(i % 7) + 1, 2, 3, 4] for i in range(16)]
    t0 = time.monotonic()
    breqs = [group.submit_generate(p, max_new=32, timeout_s=240.0)
             for p in prompts]
    batched = [r.wait(timeout=240.0) for r in breqs]
    batched_wall = time.monotonic() - t0
    ttfts = sorted(r.ttft_s() for r in breqs)
    assert all(t is not None for t in ttfts), ttfts

    # Act 3 — the same prompts one-request-at-a-time: the replica's
    # round cost is fixed by its slot batch, so serving sequentially
    # wastes it.
    t0 = time.monotonic()
    seq = [group.generate(p, max_new=32, timeout_s=240.0)
           for p in prompts]
    seq_wall = time.monotonic() - t0

    for i, (b, s) in enumerate(zip(batched, seq)):
        assert b["tokens"] == s["tokens"], \
            f"batched/sequential streams diverged for prompt {i}"
    stats = group.stats()

tokens = sum(len(b["tokens"]) for b in batched)
tps_batched = tokens / batched_wall
tps_seq = tokens / seq_wall
assert tps_batched >= 3.0 * tps_seq, (tps_batched, tps_seq)
assert stats["errors"] == 0, stats
assert stats["replies"] == 5 + 16 + 16, stats
ttft_p99 = ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]

exec(open("scripts/verify_metrics.py").read())
stamp("decode_smoke", {
    "decode_tokens_per_sec": tps_batched,
    "sequential_tokens_per_sec": tps_seq,
    "speedup_vs_sequential": tps_batched / tps_seq,
    "ttft_p99_s": ttft_p99,
})
PYEOF
fi
# Autoscale smoke (HARD): sustained admission pressure grows a real
# worker pool within ONE evaluation, the injected spawn_fail:nth=1 is
# backed off and retried to convergence, idle drains the pool back to
# min_workers with zero flap episodes (every grow strictly precedes
# every shrink), a scale-down mid-ETL loses no tasks (result parity),
# and every decision is reconstructible from autoscale/* events via
# the timeline CLI — the end-to-end proof of doc/scheduling.md's
# autoscaling story.
if [ "$rc" -eq 0 ]; then
  echo "--- autoscale smoke (pressure grow / chaos spawn / graceful drain) ---"
  as_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_TELEMETRY_DIR="$as_dir" \
    RAYDP_TPU_FAULT_PLAN="spawn_fail:nth=1" python - <<'PYEOF' \
    && as_tl=$(JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.events "$as_dir") \
    && grep -q "autoscale/decision" <<<"$as_tl" \
    && grep -q "autoscale/spawn_failed" <<<"$as_tl" \
    && echo "AUTOSCALE_SMOKE=ok" \
    || { echo "AUTOSCALE_SMOKE=failed"; dump_dashboard "$as_dir"; rc=1; }
import threading
import time

import raydp_tpu
from raydp_tpu import control, telemetry
from raydp_tpu.control import (
    Autoscaler,
    AutoscalerConfig,
    ClusterProvisioner,
)
from raydp_tpu.telemetry import events as events_mod
from raydp_tpu.utils.profiling import metrics

session = raydp_tpu.init(app_name="autoscale-smoke", num_workers=1,
                         memory_per_worker="256MB")
cluster = session.cluster
sc = Autoscaler(ClusterProvisioner(cluster), AutoscalerConfig(
    min_workers=1, max_workers=3, interval_s=0.5, up_cooldown_s=0.3,
    down_cooldown_s=0.6, idle_evals=2, spawn_retries=3, backoff_s=0.2,
))

# -- phase 1: sustained admission pressure -> grow within ONE eval.
arb = control.configure(capacity=1, admit_timeout_s=120.0)
holder = arb.acquire(telemetry.mint_job("holder"), slots=1,
                     preemptible=False)
waiter_out = {}


def waiter():
    waiter_out["lease"] = arb.acquire(
        telemetry.mint_job("starved"), slots=1, timeout=120.0,
        preemptible=False,
    )


wt = threading.Thread(target=waiter, daemon=True)
wt.start()
deadline = time.monotonic() + 10.0
while time.monotonic() < deadline and arb.report()["queue_depth"] != 1:
    time.sleep(0.02)
assert arb.report()["queue_depth"] == 1, arb.report()

_t_grow = time.monotonic()
d = sc.step()  # one evaluation under pressure must already grow
time_to_grow = time.monotonic() - _t_grow
assert d.verdict == "grow", d
assert len(cluster.alive_workers()) == 2

# -- phase 2: second grow trips spawn_fail:nth=1 -> backoff, retry,
# converge (chaos-hardened provisioning).
time.sleep(0.35)  # clear the up-cooldown
_t_grow = time.monotonic()
d = sc.step()
time_to_grow_retry = time.monotonic() - _t_grow
assert d.verdict == "grow", d
assert len(cluster.alive_workers()) == 3
snap = metrics.snapshot()["counters"]
assert snap.get("autoscale/spawn_failed", 0) == 1, snap

holder.release()
wt.join(30.0)
waiter_out["lease"].release()


# -- phase 3: scale-down mid-ETL loses no tasks (result parity).
def task(ctx, i):
    time.sleep(0.15)
    return i


items = list(range(96))
etl_out = {"res": []}


def etl():
    for base in range(0, len(items), 8):  # sequential rounds keep the
        etl_out["res"].extend(            # job in flight across drains
            cluster.map_tasks(task, items[base:base + 8], timeout=120.0)
        )


_t_etl = time.monotonic()
et = threading.Thread(target=etl, daemon=True)
et.start()
time.sleep(0.3)  # tasks in flight on all three workers
_t_drain = time.monotonic()
deadline = time.monotonic() + 60.0
while time.monotonic() < deadline and len(cluster.alive_workers()) > 1:
    sc.step()
    time.sleep(0.25)
drain_s = time.monotonic() - _t_drain
assert len(cluster.alive_workers()) == 1, cluster.alive_workers()
et.join(180.0)
etl_elapsed = time.monotonic() - _t_etl
assert etl_out["res"] == items, "tasks lost in scale-down"

# -- phase 4: zero flap episodes — all grows strictly precede all
# shrinks in the decision record.
acted = [d.verdict for d in sc.decisions
         if d.verdict in ("grow", "shrink")]
assert acted == ["grow", "grow", "shrink", "shrink"], acted

# -- phase 5: every non-steady decision is on the event timeline.
decided = [r for r in events_mod.local_events()
           if r["name"] == "autoscale/decision"]
assert len(decided) == len(
    [d for d in sc.decisions if d.verdict != "steady"]
), (len(decided), [d.verdict for d in sc.decisions])

raydp_tpu.stop()

exec(open("scripts/verify_metrics.py").read())
stamp("autoscale_smoke", {
    "time_to_grow_s": time_to_grow,
    "time_to_grow_retry_s": time_to_grow_retry,
    "drain_s": drain_s,
    "etl_tasks_per_sec": len(items) / etl_elapsed,
})
PYEOF
  rm -rf "$as_dir"
fi
# Observability smoke (HARD): an injected serve latency fault must
# drive the full SLO loop live — the time-series sampler sees the p99
# spike, the engine opens a breach within one evaluation window with
# the offending series and correlated timeline events attached,
# traffic dilution recovers it with a measured MTTR, the episode is a
# first-class MTTR entry, the raydp_slo_* families render it, and the
# dashboard CLI reconstructs it offline from the gate's event shards —
# the end-to-end proof of doc/telemetry.md's SLO engine story.
if [ "$rc" -eq 0 ]; then
  echo "--- observability smoke (SLO breach -> triage -> recovery) ---"
  obs_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_TELEMETRY_DIR="$obs_dir" \
    RAYDP_TPU_FAULT_PLAN="latency:nth=0,delay=0.8,replica=0" \
    python - <<'PYEOF' \
    && obs_cli=$(JAX_PLATFORMS=cpu python -m raydp_tpu.telemetry.dashboard "$obs_dir") \
    && grep -q "slo/breach" <<<"$obs_cli" \
    && grep -q "slo/recovered" <<<"$obs_cli" \
    && echo "OBS_SMOKE=ok" \
    || { echo "OBS_SMOKE=failed"; dump_dashboard "$obs_dir"; rc=1; }
import time

from raydp_tpu.serve import ReplicaGroup
from raydp_tpu.telemetry import events as events_mod
from raydp_tpu.telemetry import render_prometheus
from raydp_tpu.telemetry.slo import SloConfig, SloEngine, default_objectives
from raydp_tpu.telemetry.timeseries import TimeSeriesConfig, TimeSeriesSampler
from raydp_tpu.utils.profiling import metrics


def make_model():
    # Nested so cloudpickle ships it by value to the replica procs.
    def model(payloads, bucket):
        return [float(sum(p)) for p in payloads]

    return model


sampler = TimeSeriesSampler(config=TimeSeriesConfig(
    interval_s=0.05, capacity=512, max_series=512,
))
engine = SloEngine(
    store=sampler.store,
    config=SloConfig(
        interval_s=0.05, short_window_s=1.0, long_window_s=6.0,
        budget=0.2, burn_threshold=1.0, recovery_evals=2,
    ),
    objectives=[o for o in default_objectives() if o.name == "serve_p99"],
)
_t0 = time.monotonic()
with ReplicaGroup(
    replicas=1, model_fn=make_model(), label="obs-smoke",
    max_batch=1, slo_ms=10_000, restart_backoff_s=0.1,
).start() as group:
    group.predict([1, 2, 3])  # the armed clause stalls this 0.8 s
    t_fault = time.monotonic()
    breach = None
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and breach is None:
        sampler.sample()
        for tr in engine.evaluate():
            if tr["kind"] == "breach":
                breach = tr
        time.sleep(0.05)
    assert breach is not None, "no breach within the evaluation window"
    breach_detect_s = time.monotonic() - t_fault
    attrs = breach["event"]["attrs"]
    assert any(
        r["series"] == "serve/latency/p99_s" for r in attrs["top_series"]
    ), attrs
    assert isinstance(attrs["correlated"], list), attrs

    for i in range(150):  # dilute the rolling p99 below the spike
        group.predict([i, i])
    recovered = None
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and recovered is None:
        sampler.sample()
        for tr in engine.evaluate():
            if tr["kind"] == "recovered":
                recovered = tr
        time.sleep(0.05)
    assert recovered is not None, "no recovery within deadline"
    assert recovered["mttr_s"] > 0

report = events_mod.mttr_report(events_mod.local_events())
assert any(
    ep.get("start_kind") == "slo/breach"
    and ep.get("end_kind") == "slo/recovered"
    for job in report.values() for ep in job.get("episodes", [])
), report
text = render_prometheus(
    {"workers": {}, "aggregate": {}, "driver": metrics.snapshot()}
)
for family in ("raydp_slo_breaches_total", "raydp_slo_status",
               "raydp_slo_burn_rate"):
    assert family in text, family
stats = sampler.store.stats()
assert stats["memory_bytes_est"] < 32 * 1024 * 1024, stats

exec(open("scripts/verify_metrics.py").read())
stamp("obs_smoke", {
    "breach_detect_s": breach_detect_s,
    "slo_mttr_s": recovered["mttr_s"],
    "samples_per_sec": stats["samples"] / (time.monotonic() - _t0),
})
PYEOF
  rm -rf "$obs_dir"
fi
# Load smoke (HARD): the load observatory measured against a live
# replica group — a short open-loop ramp must find a FINITE capacity
# knee (saturated, not a ramp-ceiling artifact), a probe step at 50%
# of that knee must complete with zero non-shed errors, every
# completed request's queue_wait+linger+execute+reply decomposition
# must sum to its end-to-end wall within 5%, and the offline CLI must
# reconstruct the knee curve from the raw results JSONL — the
# end-to-end proof of doc/serving.md's load-observatory story.
if [ "$rc" -eq 0 ]; then
  echo "--- load smoke (knee ramp + phase provenance + offline report) ---"
  load_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_LOADGEN_RESULTS="$load_dir/results.jsonl" \
    python - <<'PYEOF' \
    && load_cli=$(JAX_PLATFORMS=cpu python -m raydp_tpu.loadgen report "$load_dir/results.jsonl") \
    && grep -q "knee: .* rps (saturated" <<<"$load_cli" \
    && grep -q "phase breakdown" <<<"$load_cli" \
    && echo "LOAD_SMOKE=ok" \
    || { echo "LOAD_SMOKE=failed"; dump_dashboard; rc=1; }
import os
import time

from raydp_tpu.loadgen import (
    GroupTarget, KneeConfig, find_knee, poisson_schedule, run_schedule,
    write_results,
)
from raydp_tpu.serve import ReplicaGroup


def make_model():
    # Nested so cloudpickle ships it by value to the replica procs.
    def model(payloads, bucket):
        time.sleep(0.012)
        return [float(sum(p)) for p in payloads]

    return model


# max_batch=1 + ~12ms model pins capacity near 2/0.012 ~ 170 rps so
# the cliff lands inside a short ramp; tiny linger keeps the knee
# about execute capacity, not the batching window.
config = KneeConfig(
    start_rps=16.0, max_rps=1024.0, step_factor=2.0,
    step_duration_s=1.0, slo_ms=150.0, shed_threshold=0.05,
    bisect_rounds=2, timeout_s=5.0, seed=0,
)
with ReplicaGroup(
    replicas=2, model_fn=make_model(), label="smoke-load",
    max_batch=1, slo_ms=5, max_queue=512, restart_backoff_s=0.2,
).start() as group:
    deadline = time.monotonic() + 30.0
    while group.stats()["replicas_alive"] < 2:
        assert time.monotonic() < deadline, "replicas never came up"
        time.sleep(0.02)
    group.predict([0] * 8, timeout_s=30.0)  # warm dispatch path
    target = GroupTarget(group)
    result = find_knee(target, config)
    # Probe step at 50% of the knee: comfortably below capacity, so
    # nothing may shed, time out, or error.
    probe = run_schedule(
        target,
        poisson_schedule(
            max(1.0, 0.5 * result.knee_rps), 1.5, seed=101
        ),
        timeout_s=config.timeout_s,
    )
    probe80 = run_schedule(
        target,
        poisson_schedule(
            max(1.0, 0.8 * result.knee_rps), 1.5, seed=202
        ),
        timeout_s=config.timeout_s,
    )

# Finite knee: the ramp confirmed a cliff rather than running off the
# top of the sweep.
assert result.saturated, result.summary()
assert 0 < result.knee_rps < config.max_rps, result.summary()

counts = probe.counts()
assert counts["ok"] == len(probe.outcomes) and counts["ok"] > 0, counts

# Latency provenance: the four additive phases reconstruct each
# request's accept->reply wall exactly, and that wall accounts for
# the client-observed end-to-end latency within 5% (plus 10ms
# absolute slack — submit admission + waiter-thread wakeup live
# outside the queue's window and jitter on a loaded CI box).
decomposed = 0
for out in probe.outcomes + probe80.outcomes:
    if out.status != "ok" or not out.phases:
        continue
    decomposed += 1
    phase_sum = sum(
        out.phases[k]
        for k in ("queue_wait", "linger", "execute", "reply")
    )
    assert abs(phase_sum - out.phases["total"]) <= 1e-6, out.phases
    gap = out.latency_s - phase_sum
    assert gap >= -0.001, (phase_sum, out.latency_s)
    assert gap <= max(0.05 * out.latency_s, 0.010), (
        phase_sum, out.latency_s, out.phases
    )
assert decomposed > 0, "no request carried a phase decomposition"

fractions = probe.phase_fractions()
additive = sum(
    fractions.get(k, 0.0)
    for k in ("queue_wait", "linger", "execute", "reply")
)
assert abs(additive - 1.0) <= 0.05, fractions

write_results(os.environ["RAYDP_TPU_LOADGEN_RESULTS"], result)

p99_80 = probe80.latency_quantile(0.99)
exec(open("scripts/verify_metrics.py").read())
stamp("load_smoke", {
    "knee_rps": result.knee_rps,
    "p99_at_knee_ms": (
        result.p99_at_knee_s * 1e3
        if result.p99_at_knee_s is not None else None
    ),
    "p99_at_80pct_knee_ms": (
        p99_80 * 1e3 if p99_80 is not None else None
    ),
    "probe_ok": counts["ok"],
    "phase_sum_checked": decomposed,
})
PYEOF
  rm -rf "$load_dir"
fi
# Sim smoke (HARD): the virtual-clock observatory (doc/simulation.md)
# — a seeded 100k-arrival diurnal+flash trace (round-tripped through
# the loadgen JSONL format) replays through the REAL
# arbiter/autoscaler/serve-queue on virtual time in seconds of wall
# clock with zero invariant violations and zero pathologies; a
# deliberately undersized pool under the same flash crowd must trip
# the shed-storm detector; and the virtual knee over the LOAD_SMOKE
# topology must agree with the real knee that gate just measured
# within 25% — the proof that the simulator predicts the same cliff
# the hardware shows.
if [ "$rc" -eq 0 ]; then
  echo "--- sim smoke (virtual-clock replay + pathology + knee cross-check) ---"
  sim_dir=$(mktemp -d)
  JAX_PLATFORMS=cpu RAYDP_TPU_SIM_TRACE_DIR="$sim_dir" \
    python - <<'PYEOF' \
    && echo "SIM_SMOKE=ok" \
    || { echo "SIM_SMOKE=failed"; rc=1; }
import json
import os

from raydp_tpu.loadgen.knee import KneeConfig
from raydp_tpu.loadgen.schedules import (
    TraceEvent, diurnal_schedule, flash_crowd_schedule,
)
from raydp_tpu.loadgen.trace import read_trace, write_trace
from raydp_tpu.sim import ScenarioConfig, run_trace, sim_knee

# Seeded 100k-arrival trace: a diurnal day with a flash crowd riding
# on top of it, round-tripped through the loadgen JSONL format so the
# sim consumes exactly what the real replay harness would.
diurnal = diurnal_schedule(1200.0, 70.0, seed=1)
flash = flash_crowd_schedule(500.0, 30.0, seed=2, burst_mult=8.0)
events = list(diurnal) + [
    TraceEvent(t=e.t + 70.0, bucket=e.bucket, size=e.size)
    for e in flash
]
assert len(events) >= 100_000, len(events)
trace_path = os.path.join(os.environ["RAYDP_TPU_SIM_TRACE_DIR"],
                          "smoke.jsonl")
write_trace(trace_path, events)
events = read_trace(trace_path)

healthy = run_trace(events, ScenarioConfig(
    hosts=16, max_batch=8, max_queue=4096, slo_ms=250.0,
))
assert healthy.completed == healthy.arrivals, (
    healthy.arrivals, healthy.completed, healthy.shed, healthy.errors
)
assert healthy.invariant_violations == [], healthy.invariant_violations
assert healthy.pathologies == [], healthy.pathologies
assert healthy.wall_s < 60.0, healthy.wall_s

# The same flash crowd over a deliberately undersized pool must trip
# the shed-storm detector — the positive control for the pathology
# plane.
storm = run_trace(flash, ScenarioConfig(
    hosts=1, max_batch=2, max_queue=64, slo_ms=50.0,
))
storm_kinds = {p["kind"] for p in storm.pathologies}
assert "shed_storm" in storm_kinds, storm.pathologies

# Virtual knee over the LOAD_SMOKE topology (2 replicas, batch 1,
# 12ms/call, tiny linger): must land within 25% of the real knee the
# load-smoke gate just measured on the same shape.
knee = sim_knee(
    ScenarioConfig(hosts=2, max_batch=1, service_ms=12.0, slo_ms=5.0,
                   max_queue=512, timeout_s=5.0),
    KneeConfig(start_rps=16.0, max_rps=1024.0, step_factor=2.0,
               step_duration_s=1.0, slo_ms=150.0, shed_threshold=0.05,
               bisect_rounds=2, timeout_s=5.0, seed=0),
)
assert knee["saturated"], knee

real_knee = None
metrics_path = os.environ.get("VERIFY_METRICS_PATH")
if metrics_path and os.path.exists(metrics_path):
    with open(metrics_path) as f:
        doc = json.load(f)
    real_knee = (doc.get("configs", {})
                    .get("load_smoke", {})
                    .get("knee_rps"))
if real_knee:
    gap = abs(knee["knee_rps"] - real_knee) / real_knee
    assert gap <= 0.25, (
        f"sim knee {knee['knee_rps']} vs real {real_knee} rps: "
        f"{gap:.0%} apart (tolerance 25%)"
    )
else:
    gap = None
    print("sim smoke: no load_smoke stamp found; knee cross-check "
          "skipped (standalone run)")

exec(open("scripts/verify_metrics.py").read())
stamp("sim_smoke", {
    "arrivals": healthy.arrivals,
    "wall_s": round(healthy.wall_s, 2),
    "events_per_sec": round(healthy.events_per_s, 1),
    "invariant_violations": len(healthy.invariant_violations),
    "pathologies_healthy": len(healthy.pathologies),
    "shed_storm_detected": 1 if "shed_storm" in storm_kinds else 0,
    "knee_rps": knee["knee_rps"],
    "real_knee_rps": real_knee,
    "knee_gap_frac": round(gap, 4) if gap is not None else None,
})
PYEOF
  rm -rf "$sim_dir"
fi
# Bench regression gate (ADVISORY): when two result files exist, diff
# the newest pair; a >10% throughput/MFU regression prints loudly but
# never fails the tier-1 gate (bench noise on shared CI boxes is real
# — promote by dropping the `|| true` once runs are on quiet hardware).
if [ "$rc" -eq 0 ]; then
  mapfile -t bench_files < <(ls -t BENCH_r*.json BENCH_partial.json 2>/dev/null | head -2)
  if [ "${#bench_files[@]}" -eq 2 ]; then
    echo "--- bench regression check (advisory) ---"
    python scripts/bench_compare.py "${bench_files[1]}" "${bench_files[0]}" || true
  fi
  # Smoke-gate metrics drift (ADVISORY): same rules, over the
  # VERIFY_METRICS.json the gates above just stamped vs the previous
  # run's stamp (preempt MTTR, serve fill, time-to-grow, SLO MTTR).
  prev_metrics="${VERIFY_METRICS_PATH%.json}.prev.json"
  if [ -f "$prev_metrics" ] && [ -f "$VERIFY_METRICS_PATH" ]; then
    echo "--- smoke-metrics drift check (advisory) ---"
    python scripts/bench_compare.py "$prev_metrics" "$VERIFY_METRICS_PATH" || true
  fi
fi
exit $rc
