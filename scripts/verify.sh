#!/usr/bin/env bash
# Tier-1 verification gate (see ROADMAP.md): the fast, CPU-only test
# suite every change must keep green. Runs from any cwd.
#
#   scripts/verify.sh [extra pytest args]
#
# Prints DOTS_PASSED=<n> (count of progress dots = passing tests) and
# exits with pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."

LOG="${T1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
# Crash black box for CI: every test-spawned process dumps a postmortem
# bundle here on crash/SIGTERM/watchdog stall; shipped on failure below.
export RAYDP_TPU_POSTMORTEM_DIR="${RAYDP_TPU_POSTMORTEM_DIR:-/tmp/raydp_tpu_postmortem.$$}"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
if [ "$rc" -ne 0 ]; then
  # Ship the black box with the failure: newest bundle's reason + last
  # flight events (no-op message when nothing crashed).
  echo "--- newest postmortem bundle (if any) ---"
  python -m raydp_tpu.telemetry.flight_recorder "$RAYDP_TPU_POSTMORTEM_DIR" || true
fi
exit $rc
