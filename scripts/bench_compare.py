#!/usr/bin/env python
"""Diff two bench result JSONs and fail on throughput/MFU regressions.

Usage::

    python scripts/bench_compare.py OLD.json NEW.json [--threshold 0.10]

Reads the per-config numeric leaves whose key names carry a rate
(``*per_sec*``) or efficiency (``mfu``) meaning, matches them between
the two files, and exits

* ``0`` — no matched metric regressed more than ``threshold``
  (default 10%);
* ``1`` — at least one regression past the threshold (each is printed);
* ``2`` — the files could not be compared (missing, unparseable, or no
  overlapping metrics) — advisory for CI: distinguish "bench got
  slower" from "bench output missing".

Two on-disk shapes are accepted transparently:

* the real ``bench.py`` result/partial shape — top-level ``configs`` /
  ``cpu_matrix`` dicts of per-benchmark entries;
* the driver wrapper shape — ``{"n", "cmd", "rc", "tail", "parsed"}``
  where ``parsed`` (when non-null) holds the real shape. A wrapper
  whose ``parsed`` is null has nothing comparable → exit 2.

Higher is better for rate/efficiency metrics (``*per_sec*``, ``mfu``,
``batch_fill``), so a regression is ``new < old × (1 - threshold)``.
Repair/startup latencies (``*mttr_s``, ``time_to_*`` — the
``VERIFY_METRICS.json`` stamps the verify.sh smoke gates write) are
lower-is-better: there the regression is the value growing. Metrics
present in only one file are reported but never fail the comparison —
benchmarks come and go across revisions.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional, Tuple

# Substrings of leaf keys that denote a higher-is-better metric.
_RATE_MARKERS = ("per_sec",)
_EXACT_KEYS = ("mfu", "batch_fill", "knee_rps", "aqe_speedup")

# Substrings that denote a lower-is-better metric (repair/startup
# latencies from the VERIFY_METRICS.json smoke stamps: preempt MTTR,
# SLO MTTR, autoscaler time-to-grow; decode time-to-first-token from
# the serve_decode section). A regression is the metric getting
# BIGGER. ``decode_tokens_per_sec`` rides _RATE_MARKERS already.
_INVERSE_MARKERS = ("mttr_s", "time_to_", "detect_s", "drain_s",
                    "ttft_")

# Sections of an entry that hold nested telemetry, not results — their
# numeric leaves (e.g. meter/rows_per_sec gauges) are point-in-time
# registry values, too noisy to gate on.
_SKIP_SECTIONS = ("telemetry", "cluster_telemetry", "profile")


def _unwrap(doc: Any) -> Optional[Dict[str, Any]]:
    """Peel the driver wrapper; None when there is no result payload."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc and ("cmd" in doc or "rc" in doc):
        parsed = doc.get("parsed")
        return parsed if isinstance(parsed, dict) else None
    return doc


def _collect(
    node: Any, prefix: str, out: Dict[str, float]
) -> None:
    if isinstance(node, dict):
        for key, value in node.items():
            if key in _SKIP_SECTIONS:
                continue
            path = f"{prefix}.{key}" if prefix else str(key)
            if isinstance(value, dict):
                _collect(value, path, out)
            elif isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                if _direction(str(key)) is not None:
                    out[path] = float(value)


def _direction(key: str) -> Optional[int]:
    """+1 higher-is-better, -1 lower-is-better, None not comparable."""
    lk = key.lower()
    leaf = lk.rsplit(".", 1)[-1]
    if leaf in _EXACT_KEYS or any(m in lk for m in _RATE_MARKERS):
        return 1
    if any(m in lk for m in _INVERSE_MARKERS):
        return -1
    return None


def extract_metrics(doc: Any) -> Dict[str, float]:
    """``{dotted.path: value}`` for every rate/MFU leaf in the result."""
    payload = _unwrap(doc)
    metrics: Dict[str, float] = {}
    if payload is None:
        return metrics
    for section in ("configs", "cpu_matrix", "chip_matrix", "analysis"):
        sub = payload.get(section)
        if isinstance(sub, dict):
            _collect(sub, section, metrics)
    # A bare top-level value (the headline metric) counts too.
    if isinstance(payload.get("value"), (int, float)) and payload.get(
        "metric"
    ):
        metrics[str(payload["metric"])] = float(payload["value"])
    return metrics


def compare(
    old: Dict[str, float], new: Dict[str, float], threshold: float
) -> Tuple[list, list, list]:
    """(regressions, improvements, only_in_one) over the common keys."""
    regressions, improvements, lonely = [], [], []
    for key in sorted(set(old) | set(new)):
        if key not in old or key not in new:
            lonely.append(key)
            continue
        o, n = old[key], new[key]
        if o <= 0:
            continue
        ratio = n / o
        if _direction(key) == -1:
            # Lower is better: a bigger value is the regression, and
            # "ratio" is inverted so the printout's slower/faster
            # wording stays truthful.
            ratio = o / n if n > 0 else float("inf")
        if ratio < 1.0 - threshold:
            regressions.append((key, o, n, ratio))
        elif ratio > 1.0 + threshold:
            improvements.append((key, o, n, ratio))
    return regressions, improvements, lonely


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="compare two bench.py result files"
    )
    parser.add_argument("old", help="baseline result JSON")
    parser.add_argument("new", help="candidate result JSON")
    parser.add_argument(
        "--threshold", type=float, default=0.10,
        help="relative regression that fails (default 0.10 = 10%%)",
    )
    args = parser.parse_args(argv)

    docs = []
    for path in (args.old, args.new):
        try:
            with open(path) as f:
                docs.append(json.load(f))
        except (OSError, ValueError) as exc:
            print(f"bench_compare: cannot read {path}: {exc}",
                  file=sys.stderr)
            return 2
    old, new = (extract_metrics(d) for d in docs)
    if not old or not new:
        print(
            "bench_compare: no comparable rate/MFU metrics "
            f"(old={len(old)}, new={len(new)}) — nothing to gate on",
            file=sys.stderr,
        )
        return 2
    regressions, improvements, lonely = compare(
        old, new, args.threshold
    )
    common = len(set(old) & set(new))
    print(
        f"bench_compare: {common} matched metric(s), "
        f"threshold {args.threshold:.0%}"
    )
    for key, o, n, ratio in regressions:
        print(f"  REGRESSION {key}: {o:,.2f} -> {n:,.2f} "
              f"({(1 - ratio) * 100:.1f}% slower)")
    for key, o, n, ratio in improvements:
        print(f"  improved   {key}: {o:,.2f} -> {n:,.2f} "
              f"(+{(ratio - 1) * 100:.1f}%)")
    for key in lonely:
        print(f"  unmatched  {key} (present in one file only)")
    if not common:
        print("bench_compare: no overlapping metrics", file=sys.stderr)
        return 2
    if regressions:
        print(f"bench_compare: {len(regressions)} regression(s) past "
              f"{args.threshold:.0%}", file=sys.stderr)
        return 1
    print("bench_compare: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
