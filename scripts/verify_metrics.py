"""Stamp machine-readable smoke-gate metrics into VERIFY_METRICS.json.

Each verify.sh smoke gate loads this file inside its heredoc
(``exec(open("scripts/verify_metrics.py").read())`` — the script cd's
to the repo root) and calls ``stamp("<gate>_smoke", {...})`` with the
numbers its assertions already computed: preempt MTTR, serve fill and
reply rate, autoscaler time-to-grow, SLO breach-detect latency and
MTTR. The leaves live under a top-level ``configs`` section so
``scripts/bench_compare.py`` diffs them with the same extraction rules
it applies to BENCH files — ``*per_sec*`` / ``batch_fill`` leaves are
higher-is-better, ``*mttr_s`` / ``time_to_*`` leaves lower-is-better.

No-op when ``VERIFY_METRICS_PATH`` is unset (gates run standalone).
"""
import json
import os


def stamp(section, leaves):
    path = os.environ.get("VERIFY_METRICS_PATH")
    if not path:
        return
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc.setdefault("configs", {})[section] = {
        k: (round(v, 6) if isinstance(v, float) else v)
        for k, v in leaves.items()
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
